"""Pluggable telemetry sinks.

Every sink implements the same four methods; the registry fans out to all
attached sinks.  Shipped sinks:

- ``JsonlSink`` — append-only JSONL event log (``MXNET_TELEMETRY_FILE``);
  the machine-readable schema ``tools/trace_summary.py`` and the bench
  harness consume (docs/OBSERVABILITY.md documents it).
- ``PrometheusSink`` — text exposition format
  (https://prometheus.io/docs/instrumenting/exposition_formats/) written
  atomically to a file for a node-exporter-style textfile collector.
- ``ProfilerSink`` — bridges counter/gauge samples into
  ``mxnet_tpu.profiler`` Counter objects, so telemetry lands as "C" series
  in the same chrome://tracing dump as user annotations.
- ``TensorBoardSink`` — scalars via the same SummaryWriter providers
  ``contrib/tensorboard.py`` uses.
"""
from __future__ import annotations

import json
import os
import threading

__all__ = ["Sink", "JsonlSink", "PrometheusSink", "ProfilerSink",
           "TensorBoardSink", "render_prometheus", "iter_scalar_samples"]


def iter_scalar_samples(snapshot):
    """Flatten a metrics snapshot to ``(key, value)`` scalars: key is
    ``name`` or ``name{k=v,...}`` with sorted labels; histograms degrade to
    their running sum.  Shared by the profiler and TensorBoard bridges so
    both views render the same series the same way."""
    for m in snapshot:
        for s in m["samples"]:
            labels = ",".join("%s=%s" % kv for kv in sorted(s["labels"].items()))
            key = m["name"] if not labels else "%s{%s}" % (m["name"], labels)
            yield key, (s["sum"] if m["type"] == "histogram" else s["value"])


class Sink:
    """Interface; methods are no-ops so subclasses override what they need."""

    def emit(self, event):
        """One timestamped event dict from ``Registry.event``."""

    def write_snapshot(self, snapshot):
        """Full metrics snapshot (list of metric dicts) from ``flush``."""

    def flush(self):
        pass

    def close(self):
        pass


def _json_default(obj):
    # numpy scalars etc.: anything with .item() degrades to a python number
    item = getattr(obj, "item", None)
    if callable(item):
        return item()
    return str(obj)


class JsonlSink(Sink):
    """One JSON object per line; events as-is, snapshots as kind="metrics".

    Write failures (unwritable path, disk full mid-run) must never kill the
    training step they instrument: the first OSError is logged once and the
    sink disables itself."""

    def __init__(self, path):
        self.path = path
        self._mu = threading.Lock()
        self._f = None
        self._broken = False

    def _file(self):
        if self._f is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._f = open(self.path, "a", encoding="utf-8")
        return self._f

    def _write(self, obj):
        line = json.dumps(obj, default=_json_default)
        with self._mu:
            if self._broken:
                return
            try:
                self._file().write(line + "\n")
            except OSError as e:
                self._broken = True
                import logging

                logging.warning(
                    "telemetry: cannot write %s (%s) — JSONL sink disabled",
                    self.path, e)

    def emit(self, event):
        self._write(event)

    def write_snapshot(self, snapshot):
        import time

        self._write({"ts": round(time.time(), 6), "kind": "metrics",
                     "metrics": snapshot})

    def flush(self):
        with self._mu:
            if self._f is not None and not self._broken:
                try:
                    self._f.flush()
                except OSError:
                    self._broken = True

    def close(self):
        with self._mu:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


def _prom_escape(value):
    """Label-VALUE escaping (exposition format 0.0.4): backslash first, then
    double-quote, then line feed — any other order double-escapes."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_escape_help(value):
    """HELP-text escaping: the format escapes only ``\\`` and line feed in
    help — escaping quotes there (the old shared escaper did) emits ``\\"``,
    which the exposition grammar does not define for help lines."""
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


def _prom_labels(labels, extra=()):
    pairs = [(k, v) for k, v in sorted(labels.items())] + list(extra)
    if not pairs:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, _prom_escape(v))
                             for k, v in pairs)


def _prom_num(v):
    if v == float("inf"):
        return "+Inf"
    return repr(float(v)) if isinstance(v, float) else str(v)


def render_prometheus(snapshot):
    """Metrics snapshot → Prometheus text exposition (version 0.0.4)."""
    lines = []
    for m in snapshot:
        name = m["name"]
        if m.get("help"):
            lines.append("# HELP %s %s" % (name, _prom_escape_help(m["help"])))
        lines.append("# TYPE %s %s" % (name, m["type"]))
        for s in m["samples"]:
            if m["type"] == "histogram":
                for le, cum in s["buckets"]:
                    lines.append("%s_bucket%s %s" % (
                        name, _prom_labels(s["labels"], [("le", le)]), cum))
                lines.append("%s_sum%s %s" % (name, _prom_labels(s["labels"]),
                                              _prom_num(s["sum"])))
                lines.append("%s_count%s %s" % (
                    name, _prom_labels(s["labels"]), s["count"]))
            else:
                lines.append("%s%s %s" % (name, _prom_labels(s["labels"]),
                                          _prom_num(s["value"])))
    return "\n".join(lines) + "\n"


class PrometheusSink(Sink):
    """Atomic whole-file exposition rewrite per snapshot (textfile-collector
    contract: readers never observe a half-written scrape).  Same failure
    contract as JsonlSink: a write error warns once and disables the sink
    rather than aborting the run it instruments."""

    def __init__(self, path):
        self.path = path
        self._broken = False

    def write_snapshot(self, snapshot):
        if self._broken:
            return
        try:
            tmp = self.path + ".tmp"
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(render_prometheus(snapshot))
            os.replace(tmp, self.path)
        except OSError as e:
            self._broken = True
            import logging

            logging.warning(
                "telemetry: cannot write %s (%s) — Prometheus sink disabled",
                self.path, e)


class ProfilerSink(Sink):
    """Mirror counter/gauge samples into ``mx.profiler`` Counters (one
    "telemetry" Domain) so chrome-trace dumps carry the series alongside
    user annotations.  Histograms are mirrored as their running sum."""

    def __init__(self):
        self._counters = {}
        self._domain = None

    def _counter(self, key):
        c = self._counters.get(key)
        if c is None:
            from .. import profiler

            if self._domain is None:
                self._domain = profiler.Domain("telemetry")
            c = self._counters[key] = profiler.Counter(self._domain, key)
        return c

    def write_snapshot(self, snapshot):
        for key, value in iter_scalar_samples(snapshot):
            self._counter(key).set_value(value)


class TensorBoardSink(Sink):
    """Scalars via a SummaryWriter (same provider probing as
    ``contrib/tensorboard.py``); ``global_step`` advances per snapshot."""

    def __init__(self, logging_dir=None, writer=None):
        if writer is None:
            try:
                from tensorboard import SummaryWriter  # 2018-era layout
            except ImportError:
                try:
                    from torch.utils.tensorboard import SummaryWriter
                except ImportError:
                    raise ImportError(
                        "TensorBoardSink requires a SummaryWriter provider "
                        "(`tensorboard` or `torch.utils.tensorboard`), or "
                        "pass writer= explicitly.")
            writer = SummaryWriter(logging_dir)
        self.writer = writer
        self.step = 0

    def write_snapshot(self, snapshot):
        self.step += 1
        for key, value in iter_scalar_samples(snapshot):
            # "name{k=v}" -> "name/k=v": slashes group series in the TB UI
            tag = key.replace("{", "/").rstrip("}")
            self.writer.add_scalar(tag, value, self.step)

    def flush(self):
        fl = getattr(self.writer, "flush", None)
        if callable(fl):
            fl()
