"""Inference quality plane — shadow-sampled tier divergence, int8
calibration-drift detection, and online tolerance-contract validation
(ISSUE 16 tentpole).

PR 15 ships fp32/bf16/int8 precision twins with *static* per-pass
tolerance contracts (``graph_passes.precision.tier_tolerance``).  This
module validates those contracts against **live traffic**, the same way
the training health plane (ISSUE 12) validates the static CastPlan
verdicts at runtime: the static contract says what a twin *should* hold
to, this plane measures what it actually does once real data drifts away
from the calibration batches.  Three signal sources:

1. **Shadow sampling** — the serving Engine deterministically samples a
   fraction (``MXNET_QUALITY_SAMPLE``, systematic like
   ``MXNET_TRACE_SAMPLE``) of completed requests served by a bf16/int8
   twin and replays them through the fp32 sibling on a background thread
   that takes the device mutex only between batches — never on the reply
   path, strictly lower priority than live dispatch, and shedding itself
   under queue pressure (``quality_shed_total``).  Per-request divergence
   (max-abs, contract fraction vs :func:`~..graph_passes.precision.
   tier_tolerance`, top-1 agreement for argmax-shaped heads) lands in
   ``tier_divergence{tier,metric}`` histograms plus a bounded ring behind
   ``Engine.stats()["quality"]``; exceeding the contract counts
   ``tier_tolerance_violations_total{tier}`` and triggers a throttled
   flight-recorder dump naming the bucket, tier, and offending head.

2. **Calibration drift** — int8 sites (exported by ``int8_rewrite`` onto
   the TierContext and stashed by the executor) compare a cheap windowed
   range sketch of live activations (:class:`RangeSketch`, epoch-rotated
   like ``slo.WindowedQuantile``) against the baked ``CalibrationTable``
   ranges: per-site ``calibration_drift_ratio`` gauges plus
   ``calibration_drift_total{site}`` when the live range escapes the
   calibrated range by ``MXNET_QUALITY_DRIFT``x — the concrete
   "re-calibrate and rebuild the twin" signal.  The baseline re-anchors
   whenever the engine (re)binds a twin, so it always tracks the table
   the serving executable was actually built from.

3. **Per-tier output distribution stats** — mean/std/extremes per head,
   accumulated host-side over the reply buffers the dispatch loop has
   already materialized (zero extra device dispatches, trainhealth
   discipline), so a silent twin regression shows up even between shadow
   samples.

Gating: :func:`plane` returns None when ``MXNET_QUALITYPLANE`` is unset —
call sites keep one ``is None`` check, no thread or ring is ever
allocated, and eval plans / jaxprs / AOT keys are byte-identical to a
build without this module (the PR 1/4 zero-overhead contract, tested in
``tests/test_qualityplane.py`` and ``ci/check_quality_plane.py``).
"""
from __future__ import annotations

import collections
import math
import os
import threading
import time

from ..base import env_flag

__all__ = ["enabled", "sample_rate", "drift_threshold", "ring_cap",
           "compare_outputs", "RangeSketch", "QualityPlane", "plane",
           "status", "DIVERGENCE_BUCKETS", "DIV_MIN", "DIV_MAX",
           "DIV_GAMMA", "NSUB"]

# -- divergence sketch geometry ----------------------------------------------
# Log-bucketed like slo.WindowedQuantile but with its own constants:
# divergence lives in [~1e-8 .. ~10] (a bf16 twin sits around 1e-3..5e-2,
# an exploded int8 twin in the 0.1..10 decade), nothing like the latency
# range, and GAMMA=2 (one bucket per octave) is plenty of resolution for
# p50/p99 over error magnitudes.
DIV_MIN = 1e-8
DIV_MAX = 10.0
DIV_GAMMA = 2.0
_N_DIV = int(math.ceil(math.log(DIV_MAX / DIV_MIN) / math.log(DIV_GAMMA))) + 2

# registry histogram buckets for tier_divergence{tier,metric} — decades
# with extra resolution around the bf16/int8 tolerance contracts
DIVERGENCE_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 5e-2, 0.1, 0.25,
                      0.5, 1.0, 2.0, 10.0)

NSUB = 6  # drift-sketch sub-windows (slo.py discipline)


def enabled():
    """``MXNET_QUALITYPLANE`` gate (docs/ENV_VARS.md) — default OFF."""
    return env_flag("MXNET_QUALITYPLANE")


def sample_rate():
    """Fraction of completed twin-served requests shadow-replayed through
    the fp32 sibling (``MXNET_QUALITY_SAMPLE``, default 0.1, clamped to
    [0, 1]) — same parse contract as ``tracing.sample_rate``."""
    try:
        r = float(os.environ.get("MXNET_QUALITY_SAMPLE", "0.1"))
    except ValueError:
        return 0.1
    return min(max(r, 0.0), 1.0)


def drift_threshold():
    """Live/calibrated maxabs ratio above which an int8 site counts a
    calibration drift (``MXNET_QUALITY_DRIFT``, default 1.5 — live
    activations 50% hotter than anything calibration saw means the
    activation scale is clipping)."""
    try:
        v = float(os.environ.get("MXNET_QUALITY_DRIFT", "1.5"))
    except ValueError:
        return 1.5
    return v if v > 1.0 else 1.5


def ring_cap():
    """Divergence rows kept in-process (``MXNET_QUALITY_RING``)."""
    try:
        v = int(os.environ.get("MXNET_QUALITY_RING", "256"))
    except ValueError:
        return 256
    return v if v > 0 else 256


def _safe(x):
    """float(x) when finite else None — the trainhealth JSON-safety rule:
    every float this plane hands to the JSONL sink or a flightrec dump
    must be strict JSON (no bare NaN/Infinity tokens)."""
    x = float(x)
    return x if math.isfinite(x) else None


# -- divergence math (pure, unit-testable) ------------------------------------
def compare_outputs(live, ref, tol):
    """Per-request divergence of a twin's outputs ``live`` vs the fp32
    sibling's ``ref`` (parallel lists of arrays, one per head) under the
    tier tolerance contract ``{"rtol", "atol"}``.

    Returns ``{"max_abs", "contract_frac", "top1_agree", "head",
    "heads": [...]}``: ``contract_frac`` is the max over elements of
    ``|a-b| / (atol + rtol*|b|)`` — the contract is violated exactly when
    it exceeds 1.0 (the ``np.allclose`` predicate, continuous-ized so a
    histogram can watch the margin shrink *before* it trips).
    ``top1_agree`` is the argmax agreement fraction for 2-D heads with
    more than one column (classification-shaped), None otherwise;
    ``head`` is the index of the worst head by contract fraction."""
    import numpy as np

    heads = []
    for i, (a, b) in enumerate(zip(live, ref)):
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        if a.size == 0 or a.shape != b.shape:
            heads.append({"head": i, "max_abs": 0.0, "contract_frac": 0.0,
                          "top1_agree": None})
            continue
        diff = np.abs(a - b)
        max_abs = float(diff.max())
        denom = tol["atol"] + tol["rtol"] * np.abs(b)
        frac = float((diff / denom).max())
        agree = None
        if a.ndim == 2 and a.shape[1] > 1:
            agree = float(np.mean(np.argmax(a, axis=1)
                                  == np.argmax(b, axis=1)))
        heads.append({"head": i, "max_abs": _safe(max_abs) or 0.0,
                      "contract_frac": _safe(frac)
                      if math.isfinite(frac) else float("inf"),
                      "top1_agree": agree})
    if not heads:
        return {"max_abs": 0.0, "contract_frac": 0.0, "top1_agree": None,
                "head": None, "heads": []}
    worst = max(heads, key=lambda h: (h["contract_frac"]
                                      if h["contract_frac"] is not None
                                      and math.isfinite(h["contract_frac"])
                                      else float("inf")))
    agrees = [h["top1_agree"] for h in heads if h["top1_agree"] is not None]
    return {"max_abs": max(h["max_abs"] for h in heads),
            "contract_frac": worst["contract_frac"],
            "top1_agree": min(agrees) if agrees else None,
            "head": worst["head"], "heads": heads}


class _DivergenceSketch:
    """Cumulative log-bucketed histogram over contract fractions — the
    per-tier ``{p50, p99, n, violations}`` summary behind SERVE_BENCH's
    ``divergence`` block.  Cumulative (not windowed): a bench run wants
    the whole serve's distribution, and the ring already provides
    recency."""

    __slots__ = ("_counts", "_n", "_violations")

    def __init__(self):
        self._counts = [0] * _N_DIV
        self._n = 0
        self._violations = 0

    def observe(self, v, violation=False):
        v = float(v)
        if not math.isfinite(v):
            i = _N_DIV - 1
        elif v <= DIV_MIN:
            i = 0
        else:
            i = 1 + int(math.floor(math.log(v / DIV_MIN)
                                   / math.log(DIV_GAMMA)))
            i = min(i, _N_DIV - 1)
        self._counts[i] += 1
        self._n += 1
        if violation:
            self._violations += 1

    def quantile(self, q):
        if self._n == 0:
            return None
        rank = max(0, int(math.ceil(q * self._n)) - 1)
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen > rank:
                if i == 0:
                    return DIV_MIN
                return min(DIV_MIN * (DIV_GAMMA ** i), DIV_MAX)
        return DIV_MAX

    def summary(self):
        return {"p50": _safe(self.quantile(0.5)) if self._n else None,
                "p99": _safe(self.quantile(0.99)) if self._n else None,
                "n": self._n, "violations": self._violations}


class RangeSketch:
    """Windowed live-activation range: ``NSUB`` epoch-rotated sub-windows
    (the ``slo.WindowedQuantile`` rotation idiom) each holding a
    ``[lo, hi]`` pair, so the drift comparison always reflects the last
    ``window_s`` of traffic and a transient spike ages out instead of
    pinning the drift gauge forever."""

    __slots__ = ("window_s", "_sub_s", "_subs")

    def __init__(self, window_s=300.0):
        self.window_s = float(window_s)
        self._sub_s = max(self.window_s / NSUB, 1e-3)
        self._subs = {}  # epoch -> [lo, hi]

    def _rotate(self, epoch):
        floor = epoch - NSUB
        for e in [e for e in self._subs if e <= floor]:
            del self._subs[e]

    def observe(self, lo, hi, now=None):
        now = time.monotonic() if now is None else now
        e = int(now / self._sub_s)
        self._rotate(e)
        s = self._subs.get(e)
        if s is None:
            self._subs[e] = [float(lo), float(hi)]
        else:
            s[0] = min(s[0], float(lo))
            s[1] = max(s[1], float(hi))

    def range(self, now=None):
        """(lo, hi) over the live window, or None when empty."""
        now = time.monotonic() if now is None else now
        self._rotate(int(now / self._sub_s))
        if not self._subs:
            return None
        los, his = zip(*self._subs.values())
        return (min(los), max(his))


# -- the host-side plane ------------------------------------------------------
class QualityPlane:
    """Per-process quality-signal sink: owns the systematic sampler, the
    per-tier divergence sketches + bounded ring, the per-site drift
    state, and the per-(tier, head) output-distribution accumulators;
    feeds the telemetry registry, the JSONL event log, and the flight
    recorder.  One per process (mirrors ``trainhealth.HealthPlane``)."""

    def __init__(self, cap=None):
        self._mu = threading.Lock()
        self._ring = collections.deque(maxlen=cap or ring_cap())
        self._rate = sample_rate()
        self._thresh = drift_threshold()
        self._n = 0          # completed twin-served requests seen
        self._sampled = 0
        self._shed = 0
        self._violations = 0
        self._div = {}       # tier -> _DivergenceSketch
        self._drift = {}     # site -> {input, calib, live, ratio, trips}
        self._outputs = {}   # (tier, head idx) -> accum dict

    # -- systematic sampler --------------------------------------------------
    def should_sample(self):
        """Advance the request counter and decide deterministically —
        the ``floor(n*rate) > floor((n-1)*rate)`` systematic rule
        (``tracing.sample_rate`` semantics): exactly ``rate`` of the
        stream, evenly spaced, reproducible across identical runs."""
        with self._mu:
            self._n += 1
            n = self._n
        if self._rate <= 0.0:
            return False
        take = math.floor(n * self._rate) > math.floor((n - 1) * self._rate)
        if take:
            with self._mu:
                self._sampled += 1
        return take

    def note_shed(self, n=1):
        """The shadow queue was full: live dispatch always wins, the
        sample is dropped and counted — never buffered unboundedly."""
        with self._mu:
            self._shed += int(n)
        from . import instrument

        if instrument.enabled():
            instrument.registry().counter(
                "quality_shed_total",
                "shadow samples dropped because the quality queue was "
                "full — live dispatch is strictly higher priority").inc(n)

    # -- shadow divergence ---------------------------------------------------
    def record_divergence(self, tier, bucket, live, ref, tol, engine=None):
        """Fold one sampled request's twin-vs-fp32 outputs into the
        plane: sketch + ring + registry histograms; a contract violation
        counts ``tier_tolerance_violations_total{tier}`` and triggers a
        throttled flightrec dump naming bucket, tier, and offending
        head.  Returns the divergence row."""
        row = compare_outputs(live, ref, tol)
        frac = row["contract_frac"]
        violation = bool(frac is None or not math.isfinite(frac)
                         or frac > 1.0)
        entry = {"tier": tier, "bucket": bucket,
                 "max_abs": row["max_abs"],
                 "contract_frac": _safe(frac) if frac is not None else None,
                 "top1_agree": row["top1_agree"], "head": row["head"],
                 "violation": violation, "unix_ts": time.time()}
        with self._mu:
            self._ring.append(entry)
            sk = self._div.get(tier)
            if sk is None:
                sk = self._div[tier] = _DivergenceSketch()
            sk.observe(frac if frac is not None else float("inf"),
                       violation=violation)
            if violation:
                self._violations += 1
        from . import instrument

        if instrument.enabled():
            r = instrument.registry()
            hist = r.histogram(
                "tier_divergence",
                "shadow-sampled divergence of a precision twin vs its "
                "fp32 sibling, per tier and metric (contract_frac > 1 "
                "is a tolerance-contract violation)",
                ("tier", "metric"), buckets=DIVERGENCE_BUCKETS)
            hist.observe(row["max_abs"], tier=tier, metric="max_abs")
            if entry["contract_frac"] is not None:
                hist.observe(entry["contract_frac"], tier=tier,
                             metric="contract_frac")
            if row["top1_agree"] is not None:
                hist.observe(max(0.0, 1.0 - row["top1_agree"]), tier=tier,
                             metric="top1_disagree")
            if violation:
                r.counter(
                    "tier_tolerance_violations_total",
                    "shadow-sampled requests whose twin-vs-fp32 "
                    "divergence exceeded the tier's tolerance contract — "
                    "the static precision contract and live traffic "
                    "disagree; alert on any nonzero rate",
                    ("tier",)).inc(tier=tier)
        instrument.event(
            "quality", signal="divergence", tier=tier, bucket=bucket,
            max_abs=entry["max_abs"],
            contract_frac=entry["contract_frac"],
            top1_agree=entry["top1_agree"], head=entry["head"],
            violation=violation, engine=engine)
        if violation:
            self._trip_violation(entry, engine)
        return entry

    def _trip_violation(self, entry, engine):
        from . import flightrec

        frec = flightrec.recorder()
        if frec is None:
            return
        frec.record("quality_violation", tier=entry["tier"],
                    bucket=entry["bucket"], head=entry["head"],
                    contract_frac=entry["contract_frac"])
        # per-reason 30s throttle is flightrec's own — a violation storm
        # costs one dump, not one per sampled request
        frec.dump("quality_violation", auto=True, engine=engine,
                  bucket=entry["bucket"], tier=entry["tier"],
                  head=entry["head"],
                  contract_frac=entry["contract_frac"],
                  max_abs=entry["max_abs"])

    # -- calibration drift ---------------------------------------------------
    def set_drift_baseline(self, sites):
        """(Re)anchor the per-site calibrated ranges — called whenever
        the engine (re)binds an int8 twin, so after a re-calibration +
        ``with_precision`` rebuild the live sketches reset and the
        comparison follows the NEW table, not the one the old executable
        was built from.  ``sites`` is the executor's stashed
        ``int8_rewrite`` export: ``{site -> {input, lo, hi, a_scale}}``."""
        with self._mu:
            self._drift = {
                str(s): {"input": d["input"],
                         "calib": (float(d["lo"]), float(d["hi"])),
                         "live": RangeSketch(), "ratio": None, "trips": 0}
                for s, d in sites.items()}

    def drift_sites(self):
        """{site: structural input name} — what the shadow worker must
        observe live ranges for."""
        with self._mu:
            return {s: d["input"] for s, d in self._drift.items()}

    def observe_site(self, site, lo, hi, now=None):
        """Fold one sampled batch's live (lo, hi) at an int8 site into
        its sketch and compare against the calibrated range: ratio =
        live maxabs / calibrated maxabs.  Above ``drift_threshold()``
        counts ``calibration_drift_total{site}``.  Returns True when the
        drift tripped."""
        with self._mu:
            d = self._drift.get(site)
            if d is None:
                return False
            d["live"].observe(lo, hi, now=now)
            rng = d["live"].range(now=now)
            clo, chi = d["calib"]
            cmax = max(abs(clo), abs(chi))
            lmax = max(abs(rng[0]), abs(rng[1])) if rng else 0.0
            ratio = (lmax / cmax) if cmax > 0 else float("inf")
            d["ratio"] = _safe(ratio)
            tripped = ratio > self._thresh
            if tripped:
                d["trips"] += 1
        from . import instrument

        if instrument.enabled():
            r = instrument.registry()
            if _safe(ratio) is not None:
                r.gauge("calibration_drift_ratio",
                        "live/calibrated activation maxabs ratio at an "
                        "int8 site (1.0 = live traffic inside the "
                        "calibrated envelope)", ("site",)).set(
                    ratio, site=site)
            if tripped:
                r.counter(
                    "calibration_drift_total",
                    "sampled batches whose live activation range escaped "
                    "an int8 site's calibrated range by more than "
                    "MXNET_QUALITY_DRIFT — re-calibrate and rebuild the "
                    "twin", ("site",)).inc(site=site)
        if tripped:
            instrument.event("quality", signal="drift", site=site,
                             ratio=_safe(ratio), threshold=self._thresh)
        return tripped

    # -- per-tier output distribution stats ----------------------------------
    def note_outputs(self, tier, outs):
        """Accumulate per-head mean/std/extremes from the reply buffers
        the dispatch loop already materialized (numpy, host-side — zero
        extra device dispatches).  Streaming merge per (tier, head)."""
        import numpy as np

        tier = tier or "fp32"
        for i, o in enumerate(outs):
            a = np.asarray(o)
            if a.dtype.kind != "f" or a.size == 0:
                continue
            n = int(a.size)
            s = float(a.sum(dtype=np.float64))
            ss = float(np.square(a, dtype=np.float64).sum())
            lo, hi = float(a.min()), float(a.max())
            key = (tier, i)
            with self._mu:
                acc = self._outputs.get(key)
                if acc is None:
                    self._outputs[key] = {"n": n, "sum": s, "sumsq": ss,
                                          "min": lo, "max": hi}
                else:
                    acc["n"] += n
                    acc["sum"] += s
                    acc["sumsq"] += ss
                    acc["min"] = min(acc["min"], lo)
                    acc["max"] = max(acc["max"], hi)

    # -- read surfaces -------------------------------------------------------
    def divergence_summary(self):
        """{tier: {p50, p99, n, violations}} over contract fractions —
        the SERVE_BENCH ``divergence`` block.  Empty dict when nothing
        was sampled yet."""
        with self._mu:
            return {t: sk.summary() for t, sk in self._div.items()}

    def rows(self):
        with self._mu:
            return list(self._ring)

    def status(self):
        """The ``Engine.stats()["quality"]`` / ``/statusz`` block."""
        with self._mu:
            div = {t: sk.summary() for t, sk in self._div.items()}
            drift = {}
            for s, d in self._drift.items():
                rng = d["live"].range()
                drift[s] = {"input": d["input"],
                            "calib": [d["calib"][0], d["calib"][1]],
                            "live": [rng[0], rng[1]] if rng else None,
                            "ratio": d["ratio"], "trips": d["trips"]}
            outputs = {}
            for (tier, head), acc in self._outputs.items():
                n = acc["n"]
                mean = acc["sum"] / n
                var = max(0.0, acc["sumsq"] / n - mean * mean)
                outputs.setdefault(tier, {})[str(head)] = {
                    "n": n, "mean": _safe(mean),
                    "std": _safe(math.sqrt(var)),
                    "min": _safe(acc["min"]), "max": _safe(acc["max"])}
            return {"seen": self._n, "sampled": self._sampled,
                    "shed": self._shed, "violations": self._violations,
                    "rows": len(self._ring),
                    "sample_rate": self._rate,
                    "drift_threshold": self._thresh,
                    "divergence": div if div else None,
                    "drift": drift if drift else None,
                    "outputs": outputs if outputs else None}


# -- process-global plane (mirrors trainhealth.plane) -------------------------
_mu = threading.Lock()
_plane = None


def plane():
    """The process QualityPlane, or None when ``MXNET_QUALITYPLANE`` is
    unset — the caller's one-check gate."""
    global _plane
    if not enabled():
        return None
    with _mu:
        if _plane is None:
            _plane = QualityPlane()
        return _plane


def status():
    """``/statusz``/CLI surface: the plane's status dict, or None when
    the gate is off (distinguishable from an enabled-but-idle plane)."""
    with _mu:
        p = _plane
    if p is None:
        return None if not enabled() else plane().status()
    return p.status()


def _reset_for_tests():
    global _plane
    with _mu:
        _plane = None
