"""Training health plane — in-graph grad/param statistics for the fused
step, runtime precision-verdict validation, rank-aware pod telemetry
(ISSUE 12 tentpole).

The fused Module step (``module/fused_step.py``) is the only training path
that matters at speed, and before this module its sole health signal was
the binary ``MXNET_NANCHECK`` flag.  With ``MXNET_TRAINHEALTH=1`` the same
donated jit also returns a compact stats pytree — global gradient norm,
per-parameter-group grad/param norms and update-to-weight ratios, the loss
head mean, and a per-group non-finite flag — all reduced on-device with
jnp ops (:func:`compute_step_stats`), so observing the step adds **zero
extra dispatches** and no host sync beyond the fit loop's existing metric
read (the stats materialize with the step outputs they share a dispatch
with).

The non-finite census is bucketed by the ISSUE 11 numerics verdict class
(``bf16_safe | fp32_accum | fp32_only``, via
``analysis.numerics.param_verdict_classes`` — each parameter group carries
the most conservative verdict among its consumer nodes).  A runtime
overflow inside a class the static analyzer *blessed* for reduced
precision is a first-class contradiction, counted in
``precision_verdict_violations_total{verdict}`` — the alertable signal
that the static CastPlan contract (PR 11) and runtime reality disagree.

The fit loop drains each step's stats into:

* the telemetry registry (``trainhealth_*`` gauges/counters, every sample
  labeled ``rank``),
* the JSONL event log (``kind: "trainhealth"``, ``rank`` field),
* a bounded in-process ring behind ``Module.trainer_stats()`` /
  :func:`status`, mirrored on the ops server's ``/statusz``,
* the flight recorder's event ring (one instant event per row), with a
  divergence (any non-finite group) triggering a crash dump that names the
  first offending group and carries the last N health rows.

Pod awareness: when ``jax.distributed`` is initialized, every sample and
JSONL line carries this process's ``rank``; each drain publishes a
``step:unix_ts`` heartbeat through the coordination-service KV store (the
same client ``parallel.dist.barrier`` uses), and **rank 0** aggregates
every rank's heartbeat into straggler/desync gauges —
``rank_step_lag_steps{rank}`` (how many steps a rank trails the
coordinator) and ``rank_heartbeat_age_seconds{rank}``.

Gating: :func:`plane` returns None when ``MXNET_TRAINHEALTH`` is unset —
call sites keep one ``is None`` check, and the fused jit's key and output
structure are byte-identical to a build without this module (the PR 1/4
zero-overhead contract, tested in ``tests/test_trainhealth.py``).
"""
from __future__ import annotations

import collections
import os
import threading
import time

from ..base import env_flag

__all__ = ["enabled", "ring_cap", "param_groups", "group_verdict_classes",
           "compute_step_stats", "HealthPlane", "plane", "status",
           "trainer_stats", "note_nonfinite_trip", "UNKNOWN_VERDICT",
           "BLESSED_VERDICTS", "DUMP_ROWS"]

# verdict-class strings are the PR 11 contract constants
# (analysis/numerics.py BF16_SAFE/FP32_ACCUM/FP32_ONLY); "unknown" is this
# module's fallback when the analyzer cannot classify (no avals, or the
# analysis itself failed — health must never fail a train step)
UNKNOWN_VERDICT = "unknown"
# classes the static analyzer blessed for reduced precision: a runtime
# non-finite there contradicts the CastPlan contract and counts into
# precision_verdict_violations_total{verdict}
BLESSED_VERDICTS = ("bf16_safe", "fp32_accum")
_VERDICT_RANK = {"bf16_safe": 0, "fp32_accum": 1, "fp32_only": 2,
                 UNKNOWN_VERDICT: 3}

DUMP_ROWS = 16  # recent health rows carried into a divergence crash dump

# parameter-name suffixes folded into one per-layer group (fc1_weight +
# fc1_bias -> group "fc1") — bounds the per-group series cardinality at
# one per layer instead of one per tensor
_GROUP_SUFFIXES = ("weight", "bias", "gamma", "beta")

_HB_PREFIX = "mxt_trainhealth/hb/"


def enabled():
    """``MXNET_TRAINHEALTH`` gate (docs/ENV_VARS.md) — default OFF."""
    return env_flag("MXNET_TRAINHEALTH")


def ring_cap():
    """Health rows kept in-process (``MXNET_TRAINHEALTH_RING``)."""
    try:
        v = int(os.environ.get("MXNET_TRAINHEALTH_RING", "256"))
    except ValueError:
        return 256
    return v if v > 0 else 256


def hb_interval_s():
    """Minimum seconds between pod heartbeat publishes/aggregations
    (``MXNET_TRAINHEALTH_HB_S``, default 1 — the slo.py ≤1/s discipline).
    The exchange is 2 blocking coordinator RPCs per rank (+ a dir scan on
    rank 0); unthrottled it would run once per training step.  ``0``
    publishes every drain (tests)."""
    try:
        return float(os.environ.get("MXNET_TRAINHEALTH_HB_S", "1"))
    except ValueError:
        return 1.0


def monitor_row_names(param_names):
    """The stat-row names the in-graph monitor route will feed for these
    parameters — ``Module.install_monitor`` matches a monitor's regex
    against this list to decide the route: a pattern that would match
    NOTHING here (e.g. ``fc1_weight``, a tensor name) keeps the un-jitted
    executor route instead of going silently blind."""
    names = []
    for group, _idxs in param_groups(param_names):
        for stat in ("grad_norm", "param_norm", "update_ratio"):
            names.append("%s:%s" % (group, stat))
    names.extend(["global:grad_norm", "loss"])
    return names


# -- static structure: groups + verdict classes -------------------------------
def param_groups(param_names):
    """Ordered ``((group_name, (param_index, ...)), ...)`` over the fused
    step's differentiable parameter list: params sharing a layer prefix
    (``fc1_weight``/``fc1_bias`` -> ``fc1``) form one group; anything
    without a known suffix is its own group."""
    order, members = [], {}
    for i, name in enumerate(param_names):
        group = name
        for suf in _GROUP_SUFFIXES:
            if name.endswith("_" + suf) and len(name) > len(suf) + 1:
                group = name[:-(len(suf) + 1)]
                break
        if group not in members:
            members[group] = []
            order.append(group)
        members[group].append(i)
    return tuple((g, tuple(members[g])) for g in order)


def group_verdict_classes(module, param_names, groups):
    """{group_name: verdict class} for a bound Module's train plan — each
    parameter takes the most conservative verdict among its consumer nodes
    (``analysis.numerics.param_verdict_classes``), each group the most
    conservative among its parameters.  Any analysis failure degrades to
    ``"unknown"`` for the affected groups: health must observe the step,
    never veto it."""
    per_param = {}
    try:
        from .. import analysis
        from ..analysis import numerics

        ctx = analysis.executor_context(module._exec, True)
        per_param = numerics.param_verdict_classes(ctx)
    except Exception:
        per_param = {}
    out = {}
    for group, idxs in groups:
        verdict = None
        for i in idxs:
            v = per_param.get(param_names[i])
            if v is None:
                continue
            if verdict is None or _VERDICT_RANK.get(v, 3) \
                    > _VERDICT_RANK.get(verdict, 3):
                verdict = v
        # a group none of whose params reach a classified node (e.g. all
        # consumers folded away) stays "unknown" — never silently "safe"
        out[group] = verdict if verdict is not None else UNKNOWN_VERDICT
    return out


# -- the traced stats reduction (runs INSIDE the fused jit) -------------------
def compute_step_stats(heads, grads, params, new_params, groups):
    """Build the health stats pytree from the fused step's own values —
    called inside ``_build_step_fn`` under ``jax.jit``, so every reduction
    here fuses into the one donated dispatch (no extra device round trip).

    Returns ``{"global_grad_norm", "loss", "grad_norm" (G,),
    "param_norm" (G,), "update_ratio" (G,), "nonfinite" (G,) bool,
    "heads_finite"}`` with G = len(groups).  ``param_norm`` is over the
    PRE-update weights, ``update_ratio`` = ||Δw|| / (||w|| + 1e-12) — the
    classic learning-rate sanity signal.  ``loss`` is the mean of the
    first output head: the loss itself for loss-head graphs
    (MakeLoss/fused detection), the mean prediction otherwise."""
    import jax.numpy as jnp

    f32 = jnp.float32
    gsq = [jnp.sum(jnp.square(g.astype(f32))) for g in grads]
    psq = [jnp.sum(jnp.square(w.astype(f32))) for w in params]
    usq = [jnp.sum(jnp.square(nw.astype(f32) - w.astype(f32)))
           for w, nw in zip(params, new_params)]
    gfin = [jnp.all(jnp.isfinite(g)) for g in grads]
    eps = jnp.asarray(1e-12, f32)

    def _tot(vals, idxs):
        tot = vals[idxs[0]]
        for i in idxs[1:]:
            tot = tot + vals[i]
        return tot

    gnorm, pnorm, ratio, nonfin = [], [], [], []
    for _name, idxs in groups:
        gnorm.append(jnp.sqrt(_tot(gsq, idxs)))
        p = jnp.sqrt(_tot(psq, idxs))
        pnorm.append(p)
        ratio.append(jnp.sqrt(_tot(usq, idxs)) / (p + eps))
        fin = gfin[idxs[0]]
        for i in idxs[1:]:
            fin = jnp.logical_and(fin, gfin[i])
        nonfin.append(jnp.logical_not(fin))
    total = gsq[0]
    for s in gsq[1:]:
        total = total + s
    heads_fin = jnp.bool_(True)
    for h in heads:
        heads_fin = jnp.logical_and(heads_fin, jnp.all(jnp.isfinite(h)))
    loss = (jnp.mean(heads[0].astype(f32)) if heads
            else jnp.asarray(0.0, f32))
    return {"global_grad_norm": jnp.sqrt(total), "loss": loss,
            "grad_norm": jnp.stack(gnorm), "param_norm": jnp.stack(pnorm),
            "update_ratio": jnp.stack(ratio), "nonfinite": jnp.stack(nonfin),
            "heads_finite": heads_fin}


# -- pod/rank plumbing --------------------------------------------------------
def _dist():
    """(coordination client or None, rank, world size) — (None, 0, 1) in
    single-process runs and whenever jax is absent/uninitialized.  Uses the
    same ``global_state.client`` handle ``parallel.dist.barrier`` does."""
    import sys

    if "jax" not in sys.modules:
        return None, 0, 1
    try:
        import jax

        n = jax.process_count()
        if n <= 1:
            return None, 0, 1
        client = getattr(jax._src.distributed.global_state, "client", None)
        return client, jax.process_index(), n
    except Exception:
        return None, 0, 1


def _publish_heartbeat(client, rank, drains):
    """Write this rank's ``drain_count:unix_ts`` heartbeat into the
    coordination KV store (the plane's monotonic drain counter, which
    unlike the stepper's step count survives stale()-rebuilds).  Keys are
    single-use in TSL, so delete-then-set; every failure is swallowed — a
    flaky coordinator must not fail training."""
    key = _HB_PREFIX + str(rank)
    try:
        client.key_value_delete(key)
    except Exception:
        pass
    try:
        client.key_value_set(key, "%d:%.3f" % (int(drains), time.time()))
    except Exception:
        pass


def _read_heartbeats(client, size):
    """{rank: (drain count, unix_ts)} for every rank that has published —
    one shared KV prefix scan (``parallel.dist.kv_prefix_ranks``, the same
    dir_get-with-try_get-fallback the dead-node check uses: one
    implementation of the jaxlib-version-sensitive client dance)."""
    from ..parallel.dist import kv_prefix_ranks

    out = {}
    for rk, value in kv_prefix_ranks(client, _HB_PREFIX, size).items():
        try:
            s, ts = str(value).split(":", 1)
            out[rk] = (int(s), float(ts))
        except (ValueError, TypeError):
            pass
    return out


def _safe(x):
    """float(x) when finite, else None — everything the plane hands to
    json consumers (the JSONL sink, flightrec dumps) must stay strict
    JSON: python's encoder emits bare ``NaN``/``Infinity`` tokens that
    spec-compliant parsers (Perfetto's JSON.parse import, jq) reject — and
    a divergence, the one event the dump exists for, is exactly when these
    values go non-finite.  The per-group ``nonfinite`` flags and the
    census stay the authoritative divergence signal."""
    import math

    x = float(x)
    return x if math.isfinite(x) else None


# -- the host-side plane ------------------------------------------------------
class HealthPlane:
    """Per-process drain target: converts the step's device stats into one
    host row, feeds registry/JSONL/flight-recorder, keeps the bounded ring
    behind ``trainer_stats()``/``status()``, and runs the pod heartbeat
    exchange.  One per process (mirrors ``flightrec.recorder``)."""

    def __init__(self, cap=None):
        self._ring = collections.deque(maxlen=cap or ring_cap())
        self._mu = threading.Lock()
        self._last = None
        self._ranks = None   # rank 0: {rank: {step, lag_steps, hb age}}
        self._trips = 0
        # monotonic drain counter — the heartbeat/straggler baseline.
        # Deliberately NOT the stepper's _nsteps: that resets on every
        # stale() rebuild (optimizer swap, gate flip), which would read
        # as a false straggler page (or mask a real one on rank 0).
        self._drained = 0
        self._last_hb = None  # monotonic of the last heartbeat exchange

    # -- drain (called once per fit-loop batch, after the metric sync) -------
    def drain(self, module, epoch=None, step=None):
        """Pop the fused stepper's pending stats and fan them out → the
        host row dict, or None when the module has none staged (legacy
        path, or no step ran).  The device reads here cost nothing extra:
        the stats share a dispatch with the step outputs the metric read
        already synced."""
        fused = getattr(module, "_fused", None)
        raw = fused.pop_health() if fused is not None else None
        if raw is None:
            return None
        t0 = time.perf_counter()
        import numpy as np

        stepno, stats = raw
        groups = fused._health_groups or ()
        verdicts = fused._health_verdicts or {}
        names = [g for g, _ in groups]
        gn = np.asarray(stats["grad_norm"], dtype=np.float64)
        pn = np.asarray(stats["param_norm"], dtype=np.float64)
        ur = np.asarray(stats["update_ratio"], dtype=np.float64)
        nf = np.asarray(stats["nonfinite"], dtype=bool)
        ggn = float(np.asarray(stats["global_grad_norm"]))
        loss = float(np.asarray(stats["loss"]))
        heads_ok = bool(np.asarray(stats["heads_finite"]))
        client, rank, size = _dist()

        bad = [names[i] for i in range(len(names)) if nf[i]]
        census = {}
        for g in bad:
            v = verdicts.get(g, UNKNOWN_VERDICT)
            census[v] = census.get(v, 0) + 1
        # every float in the row is JSON-safe (_safe: non-finite -> None);
        # the nonfinite flags/census carry the divergence signal
        row = {
            "step": int(stepno), "epoch": epoch, "fit_step": step,
            "rank": int(rank),
            "global_grad_norm": _safe(ggn), "loss": _safe(loss),
            "heads_finite": heads_ok,
            "groups": {
                names[i]: {"grad_norm": _safe(gn[i]),
                           "param_norm": _safe(pn[i]),
                           "update_ratio": _safe(ur[i]),
                           "nonfinite": bool(nf[i]),
                           "verdict": verdicts.get(names[i],
                                                   UNKNOWN_VERDICT)}
                for i in range(len(names))},
            "nonfinite_groups": bad,
            "nonfinite_census": census,
        }
        with self._mu:
            self._ring.append(row)
            self._last = row
            self._drained += 1
            drained = self._drained
        self._feed_registry(row)
        from . import instrument

        instrument.event(
            "trainhealth", rank=row["rank"], step=row["step"],
            epoch=epoch, global_grad_norm=row["global_grad_norm"],
            loss=row["loss"], heads_finite=heads_ok,
            groups=row["groups"], nonfinite_census=census)
        from . import flightrec

        frec = flightrec.recorder()
        if frec is not None:
            frec.record("trainhealth", step=row["step"], rank=row["rank"],
                        global_grad_norm=row["global_grad_norm"],
                        loss=row["loss"], nonfinite=bad)
        if client is not None:
            # throttled: heartbeats need ~1/s resolution, not one blocking
            # coordinator RPC pair per training step (hb_interval_s)
            mono = time.monotonic()
            if self._last_hb is None \
                    or mono - self._last_hb >= hb_interval_s():
                self._last_hb = mono
                _publish_heartbeat(client, rank, drained)
                if rank == 0:
                    self._aggregate(client, size, drained)
        if bad or not heads_ok:
            self._trip(row, frec)
        if instrument.enabled():
            instrument.registry().counter(
                "trainhealth_drain_seconds_total",
                "host wall seconds spent draining health stats — the "
                "plane's whole per-step overhead beyond the in-graph "
                "reductions", ("rank",)).inc(
                max(0.0, time.perf_counter() - t0), rank=str(rank))
        return row

    def _feed_registry(self, row):
        from . import instrument

        if not instrument.enabled():
            return
        r = instrument.registry()
        lr = str(row["rank"])

        def _set(gauge, value, **labels):
            if value is not None:  # _safe()'d a non-finite: gauge holds
                gauge.set(value, **labels)  # its last finite reading

        _set(r.gauge("trainhealth_global_grad_norm",
                     "global L2 gradient norm of the last fused step",
                     ("rank",)), row["global_grad_norm"], rank=lr)
        _set(r.gauge("trainhealth_loss",
                     "first-head mean of the last fused step", ("rank",)),
             row["loss"], rank=lr)
        gg = r.gauge("trainhealth_group_grad_norm",
                     "per-parameter-group L2 gradient norm",
                     ("group", "rank"))
        gp = r.gauge("trainhealth_group_param_norm",
                     "per-parameter-group L2 weight norm (pre-update)",
                     ("group", "rank"))
        gu = r.gauge("trainhealth_group_update_ratio",
                     "per-parameter-group ||delta w|| / ||w||",
                     ("group", "rank"))
        for g, s in row["groups"].items():
            _set(gg, s["grad_norm"], group=g, rank=lr)
            _set(gp, s["param_norm"], group=g, rank=lr)
            _set(gu, s["update_ratio"], group=g, rank=lr)
        r.counter("trainhealth_rows_total", "health rows drained",
                  ("rank",)).inc(rank=lr)
        if row["nonfinite_census"]:
            nft = r.counter(
                "trainhealth_nonfinite_total",
                "parameter groups with non-finite gradients, bucketed by "
                "their static numerics verdict class",
                ("verdict", "rank"))
            pvv = r.counter(
                "precision_verdict_violations_total",
                "runtime non-finite in a verdict class the static "
                "numerics analyzer blessed for reduced precision — the "
                "CastPlan contract and runtime reality disagree; alert on "
                "any nonzero rate", ("verdict", "rank"))
            for v, n in row["nonfinite_census"].items():
                nft.inc(n, verdict=v, rank=lr)
                if v in BLESSED_VERDICTS:
                    pvv.inc(n, verdict=v, rank=lr)

    def _aggregate(self, client, size, my_drains):
        """Rank 0: fold every rank's heartbeat into straggler gauges —
        lag is measured in DRAINS (one per fit-loop batch), against this
        plane's own monotonic counter."""
        from . import instrument

        now = time.time()
        hbs = _read_heartbeats(client, size)
        r = instrument.registry() if instrument.enabled() else None
        agg = {}
        for rk in range(size):
            st, ts = hbs.get(rk, (None, None))
            lag = None if st is None else max(0, int(my_drains) - st)
            age = None if ts is None else max(0.0, now - ts)
            agg[rk] = {"drains": st, "lag_steps": lag,
                       "heartbeat_age_s": None if age is None
                       else round(age, 3)}
            if r is not None and lag is not None:
                r.gauge("rank_step_lag_steps",
                        "steps this rank trails rank 0's last health "
                        "drain — a persistent nonzero value is a "
                        "straggler or a desynced loop",
                        ("rank",)).set(lag, rank=str(rk))
            if r is not None and age is not None:
                r.gauge("rank_heartbeat_age_seconds",
                        "seconds since this rank's last health heartbeat",
                        ("rank",)).set(age, rank=str(rk))
        with self._mu:
            self._ranks = agg

    def _trip(self, row, frec):
        """A divergence: name the first non-finite group and dump the
        flight recorder (auto-throttled per reason like every other
        trigger).  The plane records and alerts — ``MXNET_NANCHECK`` is
        the path that *raises*."""
        with self._mu:
            self._trips += 1
            recent = list(self._ring)[-DUMP_ROWS:]
        first = (row["nonfinite_groups"][0] if row["nonfinite_groups"]
                 else "<heads>")
        verdict = row["groups"].get(first, {}).get("verdict",
                                                   UNKNOWN_VERDICT)
        from . import instrument

        instrument.event("trainhealth_trip", rank=row["rank"],
                         step=row["step"], group=first, verdict=verdict)
        if frec is not None:
            frec.dump("trainhealth", auto=True, group=first,
                      verdict=verdict, step=row["step"], rank=row["rank"],
                      health_rows=recent)

    # -- read surfaces -------------------------------------------------------
    def last_row(self):
        with self._mu:
            return self._last

    def rows(self):
        with self._mu:
            return list(self._ring)

    def status(self):
        """The ``/statusz`` block: last row + per-rank heartbeat view."""
        with self._mu:
            return {"last": self._last, "rows": len(self._ring),
                    "trips": self._trips, "ranks": self._ranks}


# -- process-global plane (mirrors flightrec.recorder) ------------------------
_mu = threading.Lock()
_plane = None


def plane():
    """The process HealthPlane, or None when ``MXNET_TRAINHEALTH`` is
    unset — the caller's one-check gate."""
    global _plane
    if not enabled():
        return None
    with _mu:
        if _plane is None:
            _plane = HealthPlane()
        return _plane


def status():
    """``/statusz``/CLI surface: the plane's status dict, or None when the
    gate is off (distinguishable from an enabled-but-idle plane)."""
    with _mu:
        p = _plane
    if p is None:
        return None if not enabled() else plane().status()
    return p.status()


def trainer_stats():
    """The last drained health row (host floats), or None — the surface
    behind ``Module.trainer_stats()``.  Authoritative without telemetry,
    like ``Engine.stats()``."""
    with _mu:
        p = _plane
    return p.last_row() if p is not None else None


def _reset_for_tests():
    global _plane
    with _mu:
        _plane = None


# -- MXNET_NANCHECK flight-recorder wiring (ISSUE 12 satellite) ---------------
def note_nonfinite_trip(where, step, detail=None):
    """A nancheck trip is about to raise: push the context into the flight
    recorder and dump — the post-mortem for a divergence now includes the
    recent request/step timeline plus the last health rows (when the
    trainhealth plane is live).  Explicit dump (never throttled): a raise
    follows, there is no second chance to write the black box."""
    from . import flightrec

    frec = flightrec.recorder()
    if frec is None:
        return None
    frec.record("nancheck", where=where, step=step,
                detail=detail or "")
    with _mu:
        p = _plane
    rows = p.rows()[-DUMP_ROWS:] if p is not None else []
    return frec.dump("nancheck", where=where, step=step,
                     detail=detail or "", health_rows=rows)
