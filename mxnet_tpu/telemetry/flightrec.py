"""Crash-dump flight recorder — the black box for failures under load
(ISSUE 10).

An always-cheap bounded ring of recent request-lifecycle and step events:
one dict append per event, fixed memory (``deque(maxlen=...)``), no file
I/O until something goes wrong.  On a trigger — a batch model error, an
SLO breach (``telemetry/slo.py`` ``on_breach``), a training divergence
(``telemetry/trainhealth.py``: a non-finite parameter group, or an
``MXNET_NANCHECK`` trip about to raise — ISSUE 12), an explicit
:meth:`dump`, or ``SIGUSR2`` — the ring is written to
``$MXNET_FLIGHTREC_DIR`` as
Chrome-trace JSON: events reuse the tracing span record shape
(``telemetry/tracing.py`` export — ``ph:"X"`` with ``ts``/``dur`` in the
shared ``mx.profiler`` perf_counter microsecond timebase, ``ph:"i"`` for
instants), so a dump opens directly in Perfetto and ``tools/trace_merge.py``
can align it with a live trace via the embedded ``clock_sync``.

Unlike tracing (sampled, opt-in, exported at exit), the recorder keeps
only the recent past and writes only on failure — it is the thing you read
*after* the 3 a.m. page, for the bugs that only reproduce under load.

Gating: :func:`recorder` returns None when ``MXNET_FLIGHTREC_DIR`` is
unset — call sites keep one ``is None`` check (the PR 1/4 zero-overhead
contract, tested).  Automatic dumps (error/breach triggers) are throttled
to one per :data:`MIN_AUTO_DUMP_S` so a sustained breach cannot storm the
disk; explicit ``dump()`` and SIGUSR2 always write.
"""
from __future__ import annotations

import json
import os
import threading
import time

from ..profiler import _now_us  # shared host timebase with tracing/profiler

__all__ = ["enabled", "flightrec_dir", "FlightRecorder", "recorder",
           "record", "dump", "RING_CAP", "MIN_AUTO_DUMP_S"]

RING_CAP = 4096          # events kept; oldest evicted
MIN_AUTO_DUMP_S = 30.0   # throttle for error/breach-triggered dumps
_PID = 0                 # chrome-trace process id (matches tracing export)


def enabled():
    return bool(os.environ.get("MXNET_FLIGHTREC_DIR", "").strip())


def _process_rank():
    """jax.distributed rank for multi-process runs, else None.  Consulted
    only at dump time (rare) and only when jax is already loaded — a
    process that never touched jax must not initialize a backend to write
    a crash dump."""
    import sys

    if "jax" not in sys.modules:
        return None
    try:
        import jax

        return jax.process_index() if jax.process_count() > 1 else None
    except Exception:
        return None


def flightrec_dir():
    return os.environ.get("MXNET_FLIGHTREC_DIR", "").strip()


class FlightRecorder:
    """One bounded event ring + the dump writer.

    ``record`` is the hot-path call: build one small dict, append to a
    ``deque`` (GIL-atomic) — no lock, no I/O, no time syscall beyond the
    shared ``_now_us``.  ``dump`` snapshots the ring and writes atomically
    (tmp + rename); write failures warn once and disable dumping rather
    than failing the serving path that triggered them (the JsonlSink
    contract)."""

    def __init__(self, directory, cap=RING_CAP, min_auto_dump_s=None):
        import collections

        self.directory = directory
        self._ring = collections.deque(maxlen=cap)
        self._dump_mu = threading.Lock()
        self._min_auto_s = (MIN_AUTO_DUMP_S if min_auto_dump_s is None
                            else float(min_auto_dump_s))
        self._last_auto = {}  # reason -> monotonic of last auto dump
        self._seq = 0
        self._broken = False

    # -- hot path ------------------------------------------------------------
    def record(self, name, dur_s=None, **args):
        """Append one event.  ``dur_s`` set ⇒ a completed span that ENDED
        now (``ts`` is backdated so the slice renders where the work ran);
        None ⇒ an instant event."""
        now = _now_us()
        if dur_s is not None:
            ev = {"name": name, "cat": "flightrec", "ph": "X",
                  "ts": round(now - dur_s * 1e6, 3),
                  "dur": round(dur_s * 1e6, 3), "pid": _PID,
                  "tid": threading.get_ident() % 1_000_000, "args": args}
        else:
            ev = {"name": name, "cat": "flightrec", "ph": "i", "s": "t",
                  "ts": round(now, 3), "pid": _PID,
                  "tid": threading.get_ident() % 1_000_000, "args": args}
        self._ring.append(ev)  # deque append is atomic under the GIL

    # -- dump ----------------------------------------------------------------
    def dump(self, reason="explicit", auto=False, **meta):
        """Write the ring → the dump path, or None (throttled auto dump,
        empty ring, or a previously failed directory).  The auto throttle
        is per REASON: a sustained SLO breach must not starve the dump for
        a later batch error."""
        with self._dump_mu:
            if self._broken:
                return None
            now = time.monotonic()
            last = self._last_auto.get(reason)
            if auto and last is not None \
                    and now - last < self._min_auto_s:
                return None
            evs = list(self._ring)
            if not evs:
                return None
            if auto:
                self._last_auto[reason] = now
            self._seq += 1
            seq = self._seq
        rank = _process_rank()
        pname = "mxnet_tpu flight recorder" if rank is None \
            else "mxnet_tpu flight recorder (rank %d)" % rank
        clock_args = {"unix_ts": round(time.time(), 6),
                      "trace_ts_us": round(_now_us(), 3)}
        if rank is not None:
            # rank rides the clock_sync args so tools/trace_merge.py can
            # merge per-rank dumps onto rank-labeled tracks (ISSUE 12)
            clock_args["rank"] = rank
        payload = {
            "traceEvents": [
                {"name": "process_name", "ph": "M", "pid": _PID,
                 "args": {"name": pname}},
                {"name": "clock_sync", "ph": "M", "pid": _PID,
                 "args": clock_args},
            ] + evs,
            "displayTimeUnit": "ms",
            "flightrec": dict(meta, reason=str(reason), pid=os.getpid(),
                              unix_ts=round(time.time(), 6),
                              events=len(evs),
                              **({"rank": rank} if rank is not None
                                 else {})),
        }
        path = os.path.join(
            self.directory,
            "flightrec-%d-%03d-%s.json" % (os.getpid(), seq,
                                           str(reason).replace("/", "_")))
        try:
            os.makedirs(self.directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
        except OSError as e:
            with self._dump_mu:
                self._broken = True
            import logging

            logging.warning("flightrec: cannot write %s (%s) — recorder "
                            "dumps disabled", path, e)
            return None
        return path

    def __len__(self):
        return len(self._ring)


# -- process-global recorder (mirrors instrument.registry) --------------------
_mu = threading.Lock()
_recorder = None
_signal_armed = False


def recorder():
    """The process recorder, or None when ``MXNET_FLIGHTREC_DIR`` is unset
    — the caller's one-check gate.  One recorder per process: serving and
    the fit loop share a single timeline, which is the point of a black
    box.  The SIGUSR2 dump hook is armed on first creation (main thread
    only; elsewhere the explicit ``dump()`` surfaces remain)."""
    global _recorder, _signal_armed
    if not enabled():
        return None
    with _mu:
        if _recorder is None or _recorder.directory != flightrec_dir():
            _recorder = FlightRecorder(flightrec_dir())
        if not _signal_armed:
            try:
                import signal

                signal.signal(signal.SIGUSR2, _on_sigusr2)
                _signal_armed = True
            except (ValueError, OSError, AttributeError):
                # not the main thread, or no SIGUSR2 on this platform
                _signal_armed = True
        return _recorder


def _on_sigusr2(signum, frame):
    # NEVER dump from the signal frame: the interrupted main thread may be
    # holding _mu or the recorder's _dump_mu mid-call (both non-reentrant),
    # and file I/O inside a handler is unsafe anyway — hand the work to a
    # one-shot thread and return immediately
    threading.Thread(target=_signal_dump, name="mxnet-flightrec-sigusr2",
                     daemon=True).start()


def _signal_dump():
    with _mu:
        r = _recorder
    if r is not None:
        r.dump("sigusr2")


def _reset_for_tests():
    global _recorder, _signal_armed
    with _mu:
        _recorder = None
        _signal_armed = False


def record(name, dur_s=None, **args):
    """Module-level convenience: record when enabled, else no-op (one env
    read — for call sites that don't hold a recorder handle)."""
    r = recorder()
    if r is not None:
        r.record(name, dur_s=dur_s, **args)


def dump(reason="explicit", **meta):
    """Module-level explicit dump → path or None."""
    r = recorder()
    if r is None:
        return None
    return r.dump(reason, **meta)
