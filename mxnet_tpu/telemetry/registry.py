"""Typed runtime-metric registry — Counter / Gauge / Histogram with labels.

The measurement substrate for the whole stack (ISSUE 1 tentpole): hot paths
record into these types, sinks (``telemetry.sinks``) serialize snapshots to
JSONL / Prometheus text / the chrome-trace profiler.  Values live behind the
profiler's ``_AtomicValue`` primitive so concurrent producers (data workers,
the dist barrier thread, user callbacks) never lose increments.

The registry itself carries no policy: it does not read environment
variables and never imports jax.  Gating lives in ``telemetry.instrument``;
a bare ``Registry()`` is always safe to construct (tests do).
"""
from __future__ import annotations

import threading
import time

from ..profiler import _AtomicValue

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "MetricError",
           "DEFAULT_BUCKETS"]

# Prometheus' default duration buckets — right-sized for step/compile seconds
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class MetricError(ValueError):
    """Metric misuse: type/label-set mismatch or invalid sample."""


class _Metric:
    typ = None

    def __init__(self, name, help="", labelnames=()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children = {}  # label-value tuple -> child cell
        self._mu = threading.Lock()

    def _key(self, labels):
        if set(labels) != set(self.labelnames):
            raise MetricError(
                "%s %r expects labels %s, got %s"
                % (self.typ, self.name, sorted(self.labelnames), sorted(labels)))
        return tuple(str(labels[k]) for k in self.labelnames)

    def _child(self, labels):
        key = self._key(labels)
        with self._mu:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            return child

    def _items(self):
        with self._mu:
            return list(self._children.items())

    def samples(self):
        """→ list of {"labels": {...}, ...} sample dicts (one per label set)."""
        out = []
        for key, child in sorted(self._items()):
            labels = dict(zip(self.labelnames, key))
            out.append(self._sample(labels, child))
        return out

    def snapshot(self):
        return {"name": self.name, "type": self.typ, "help": self.help,
                "samples": self.samples()}


class Counter(_Metric):
    """Monotonic accumulator (samples/s numerators, bytes moved, compiles)."""

    typ = "counter"

    def _new_child(self):
        return _AtomicValue(0.0)

    def inc(self, amount=1.0, **labels):
        if amount < 0:
            raise MetricError("counter %r cannot decrease (got %r)"
                              % (self.name, amount))
        return self._child(labels).add(amount)

    def value(self, **labels):
        return self._child(labels).get()

    def _sample(self, labels, child):
        return {"labels": labels, "value": child.get()}


class Gauge(_Metric):
    """Point-in-time value (bytes_in_use, last loss, samples/s)."""

    typ = "gauge"

    def _new_child(self):
        return _AtomicValue(0.0)

    def set(self, value, **labels):
        return self._child(labels).set(float(value))

    def inc(self, amount=1.0, **labels):
        return self._child(labels).add(amount)

    def dec(self, amount=1.0, **labels):
        return self._child(labels).add(-amount)

    def value(self, **labels):
        return self._child(labels).get()

    def _sample(self, labels, child):
        return {"labels": labels, "value": child.get()}


class _HistogramCell:
    __slots__ = ("_mu", "buckets", "counts", "sum", "count")

    def __init__(self, buckets):
        self._mu = threading.Lock()
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        value = float(value)
        i = 0
        for i, le in enumerate(self.buckets):
            if value <= le:
                break
        else:
            i = len(self.buckets)
        with self._mu:
            self.counts[i] += 1
            self.sum += value
            self.count += 1

    def snapshot(self):
        with self._mu:
            return {"counts": list(self.counts), "sum": self.sum,
                    "count": self.count}


class Histogram(_Metric):
    """Bucketed distribution (step seconds, data-wait seconds)."""

    typ = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=None):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets if buckets is not None
                                    else DEFAULT_BUCKETS))

    def _new_child(self):
        return _HistogramCell(self.buckets)

    def observe(self, value, **labels):
        self._child(labels).observe(value)

    def value(self, **labels):
        return self._child(labels).snapshot()

    def _sample(self, labels, child):
        snap = child.snapshot()
        cum, edges = 0, []
        for le, n in zip(self.buckets, snap["counts"]):
            cum += n
            edges.append([le, cum])
        edges.append(["+Inf", snap["count"]])
        return {"labels": labels, "count": snap["count"], "sum": snap["sum"],
                "buckets": edges}


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Registry:
    """Named metrics + attached sinks.

    ``counter``/``gauge``/``histogram`` are get-or-create (idempotent across
    call sites); asking for an existing name with a different type or label
    set raises ``MetricError`` instead of silently splitting the series.
    """

    def __init__(self):
        self._metrics = {}
        self._mu = threading.Lock()
        self._sinks = []

    # -- metric accessors ---------------------------------------------------
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labelnames, **kw)
                return m
        if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
            raise MetricError(
                "metric %r already registered as %s%s; requested %s%s"
                % (name, m.typ, m.labelnames, cls.typ, tuple(labelnames)))
        return m

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None):
        h = self._get_or_create(Histogram, name, help, labelnames,
                                buckets=buckets)
        if buckets is not None and h.buckets != tuple(sorted(buckets)):
            raise MetricError(
                "histogram %r already registered with buckets %s; requested %s"
                % (name, h.buckets, tuple(sorted(buckets))))
        return h

    def get(self, name):
        with self._mu:
            return self._metrics.get(name)

    # -- aggregate reads (bench summary / Speedometer) ----------------------
    def total(self, name, default=0.0):
        """Sum of a counter/gauge across all label sets (0 if absent)."""
        m = self.get(name)
        if m is None or m.typ == "histogram":
            return default
        return sum(s["value"] for s in m.samples()) or default

    def max_value(self, name, default=None):
        m = self.get(name)
        if m is None or m.typ == "histogram":
            return default
        vals = [s["value"] for s in m.samples()]
        return max(vals) if vals else default

    def hist_sum(self, name, default=0.0):
        m = self.get(name)
        if m is None or m.typ != "histogram":
            return default
        return sum(s["sum"] for s in m.samples()) or default

    def hist_quantile(self, name, q, default=None):
        """Estimated q-quantile of a histogram, merged across all label
        sets (``histogram_quantile``-style linear interpolation inside the
        rank's bucket; a rank landing in the +Inf bucket clamps to the top
        finite edge).  ``default`` when the metric is absent/empty — the
        bench ``summary()`` serve-latency keys read this."""
        m = self.get(name)
        if m is None or m.typ != "histogram":
            return default
        samples = m.samples()
        edges = m.buckets
        counts = [0] * (len(edges) + 1)
        total = 0
        for s in samples:
            prev = 0
            for i, (_, cum) in enumerate(s["buckets"]):
                counts[i] += cum - prev
                prev = cum
            total += s["count"]
        if total == 0:
            return default
        rank = min(max(float(q), 0.0), 1.0) * total
        cum, lo = 0, 0.0
        for i, le in enumerate(edges):
            if counts[i] and cum + counts[i] >= rank:
                frac = (rank - cum) / counts[i]
                return lo + (le - lo) * min(max(frac, 0.0), 1.0)
            cum += counts[i]
            lo = le
        return edges[-1] if edges else default

    # -- sinks / events -----------------------------------------------------
    def add_sink(self, sink):
        with self._mu:
            self._sinks.append(sink)
        return sink

    def remove_sink(self, sink):
        with self._mu:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def sinks(self):
        with self._mu:
            return list(self._sinks)

    def event(self, kind, **fields):
        """Append one timestamped event to every sink's stream (JSONL line)."""
        ev = {"ts": round(time.time(), 6), "kind": kind}
        ev.update(fields)
        for sink in self.sinks():
            sink.emit(ev)
        return ev

    def collect(self):
        """→ list of metric snapshot dicts (the JSONL "metrics" schema)."""
        with self._mu:
            metrics = list(self._metrics.values())
        return [m.snapshot() for m in metrics]

    def flush(self):
        """Write a metrics snapshot through every sink and flush them."""
        snap = self.collect()
        for sink in self.sinks():
            sink.write_snapshot(snap)
            sink.flush()
        return snap

    def close(self):
        for sink in self.sinks():
            sink.close()
