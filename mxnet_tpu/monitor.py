"""Monitor — per-tensor training statistics (reference ``python/mxnet/monitor.py:33``).

Two observation routes (ISSUE 12):

* **In-graph (default on a fused-step Module).**  ``Module.install_monitor``
  with ``monitor_all=False`` keeps the one-donated-dispatch fused step and
  feeds the monitor the trainhealth stats computed *inside* the jit —
  ``<group>:grad_norm`` / ``:param_norm`` / ``:update_ratio`` rows plus
  ``global:grad_norm`` and ``loss``, pattern-filtered by the monitor's
  regex.  Before this route, installing a monitor silently forced the whole
  training run onto the legacy un-jitted path.
* **Un-jitted executor callback (``monitor_all=True``, the escape hatch).**
  Hooks the executor's monitor callback (reference
  ``include/mxnet/executor.h:172``, ``GraphExecutor::ExecuteMonCallback``
  graph_executor.cc:1562; here ``Executor.set_monitor_callback``, which runs
  forward un-jitted so EVERY node output — and with ``monitor_all`` every
  node input — is observable).  Forces the legacy path: full observability
  at legacy speed.

Typical use::

    mon = mx.monitor.Monitor(100, norm_stat)
    mod.install_monitor(mon)   # or mon.install(executor)
    ...
    mon.tic(); mod.forward(batch); print(mon.toc_print())
"""
from __future__ import annotations

import re

import numpy as np

__all__ = ["Monitor"]


class Monitor:
    """Collect ``stat_func`` of matching tensors every ``interval`` batches.

    Parameters mirror the reference: ``interval`` (batches between actives),
    ``stat_func`` (ndarray → scalar/ndarray stat; default mean(|x|)),
    ``pattern`` (regex on tensor names), ``sort`` (sort results by name).
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        if stat_func is None:

            def stat_func(x):
                return np.abs(x).mean()

        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        # reference monitor.py:66 — monitor inputs+outputs of every node,
        # not just outputs; install() inherits this unless overridden
        self.monitor_all = monitor_all

    # executor callback — receives (name, value) per node output
    def _stat_helper(self, name, value):
        if not self.activated or not self.re_prog.match(name):
            return
        arr = np.asarray(value)
        self.queue.append((self.step, name, self.stat_func(arr)))

    def install(self, exe, monitor_all=None):
        """Attach to an executor (reference Monitor.install) — the
        un-jitted per-node route; ``Module.install_monitor`` prefers the
        in-graph route for fused-step modules (module docstring)."""
        if monitor_all is None:
            monitor_all = self.monitor_all
        exe.set_monitor_callback(self._stat_helper, monitor_all)
        self.exes.append(exe)

    def observe(self, name, value):
        """Feed one (name, value) row from outside an executor callback —
        the in-graph route (``FusedStepper.feed_monitor``) delivers the
        fused step's trainhealth stats here.  Same interval/pattern/stat
        discipline as the executor callback."""
        self._stat_helper(name, value)

    def tic(self):
        """Start collecting for this batch if the interval has elapsed."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Stop collecting; returns list of (step, tensor_name, stat)."""
        if not self.activated:
            return []
        self.activated = False
        res = []
        queue = sorted(self.queue, key=lambda q: q[1]) if self.sort else self.queue
        for n, k, v in queue:
            res.append((n, k, str(v)))
        self.queue = []
        return res

    def toc_print(self):
        """toc() + log each stat line (reference toc_print)."""
        res = self.toc()
        for n, k, v in res:
            print("Batch: %7d %30s %s" % (n, k, v))
        return res
