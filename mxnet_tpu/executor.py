"""Executor — compiled runtime for Symbol graphs.

TPU-native replacement for the reference GraphExecutor
(``src/executor/graph_executor.cc:513``): instead of NNVM passes + engine
scheduling, ``bind`` composes the registry's pure functions over the DAG and
hands the whole thing to ``jax.jit``.  XLA performs memory planning
(PlanMemory), op fusion (bulking), and scheduling; gradients come from
``jax.vjp`` (the nnvm::Gradient pass).  Aux states (BatchNorm moving stats)
are extra functional outputs folded back after each training forward —
replacing the reference's in-place aux mutation.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray, _wrap, array

__all__ = ["Executor"]


class Executor:
    """Compiled forward/backward runner (reference include/mxnet/executor.h:53)."""

    def __init__(self, symbol, ctx=None, args=None, args_grad=None, grad_req="write", aux_states=None):
        self._symbol = symbol
        self._ctx = ctx
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self._out_names = symbol.list_outputs()
        self.arg_dict = self._to_dict(args, self._arg_names, "args")
        self.aux_dict = self._to_dict(aux_states, self._aux_names, "aux_states")
        self.grad_dict = self._to_dict(args_grad, self._arg_names, "args_grad", allow_none=True) or {}
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self._arg_names, grad_req))
        else:
            self._grad_req = dict(grad_req or {})
        self.outputs = []
        self._monitor = None
        self._monitor_all = False
        self._fwd_cache = {}
        self._bwd_cache = {}  # (diff_names, ones_ct, mode) -> jitted bwd
        from . import graph_passes

        # gate snapshot at bind time: one executor never mixes optimized
        # and raw plans even if MXNET_GRAPH_PASSES flips mid-process (a
        # re-bind — Module.reshape, Predictor.with_shapes — re-reads it)
        self._graph_passes = graph_passes.enabled()
        # precision tier snapshot (ISSUE 15): MXNET_PRECISION_TIER at bind
        # time, overridable via set_precision_tier (Predictor.with_precision
        # builds explicit twins that way).  Rides on the pass layer — with
        # MXNET_GRAPH_PASSES=0 the tier is inert and the plan stays raw.
        self._precision_tier = graph_passes.precision.tier() \
            if self._graph_passes else None
        self._calibration = None  # int8 tier's CalibrationTable, if any
        self._opt_cache = {}     # is_train -> FINAL (plan, heads, const_env)
        self._struct_cache = {}  # is_train -> structural (pre-tier) triple
        self._pass_stats = {}  # "train"/"eval" -> graph_passes.optimize stats
        self._tier_stats = None  # tier-pass rows of the lowered eval plan
        self._int8_sites = {}  # int8_rewrite's drift-baseline export
        self._plan = self._make_plan()

    # -- array plumbing -----------------------------------------------------
    def _to_dict(self, arrays, names, what, allow_none=False):
        if arrays is None:
            if allow_none:
                return None
            return {}
        if isinstance(arrays, dict):
            return dict(arrays)
        if isinstance(arrays, (list, tuple)):
            if len(arrays) != len(names):
                raise MXNetError(
                    "%s length %d != expected %d (%s)" % (what, len(arrays), len(names), names)
                )
            return {n: a for n, a in zip(names, arrays) if a is not None}
        raise TypeError(type(arrays))

    @property
    def arg_arrays(self):
        return [self.arg_dict.get(n) for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict.get(n) for n in self._aux_names]

    # -- plan ---------------------------------------------------------------
    def _make_plan(self):
        """Static execution plan: topological node list with resolved input
        slots, random-key folding per stochastic node, aux-update metadata.
        Capture itself lives in ``graph_passes.capture`` (shared with the
        standalone node-count surface); the pass pipeline runs lazily per
        mode in :meth:`_opt_plan`."""
        from .graph_passes import capture

        plan, self._head_names = capture(self._symbol)
        return plan

    def _structural_plan(self, is_train):
        """The STANDARD pipeline's result for ``is_train`` — ``(plan,
        head_names, const_env)`` before any precision-tier rewrite.  This
        is the plan ``precision_plan()`` describes (the CastPlan contract
        is defined over the fp32 graph the tier rewrites) and the plan
        :func:`graph_passes.precision.calibrate` replays.

        With ``MXNET_GRAPH_PASSES`` off (snapshot at bind) this returns the
        raw captured plan untouched — byte-identical lowering to a build
        without the pass layer."""
        is_train = bool(is_train)
        hit = self._struct_cache.get(is_train)
        if hit is None:
            if not self._graph_passes:
                hit = (self._plan, self._head_names, None)
            else:
                from . import graph_passes, telemetry

                g, stats = graph_passes.optimize(
                    self._plan, self._head_names, is_train)
                self._pass_stats[stats["mode"]] = stats
                telemetry.note_graph_passes(
                    stats["nodes_pre"], stats["nodes_post"],
                    stats["seconds"], mode=stats["mode"])
                hit = (list(g.entries), list(g.heads),
                       g.constants or None)
            self._struct_cache[is_train] = hit
        return hit

    def _opt_plan(self, is_train):
        """The plan :meth:`_graph_fn` evaluates for ``is_train`` —
        ``(plan, head_names, const_env)``, where ``const_env`` seeds the
        evaluation env with pass-baked constants (None when nothing baked).

        = :meth:`_structural_plan`, plus — on EVAL plans of an executor
        whose precision tier is set (ISSUE 15) — the tier pass list
        (``graph_passes.precision``): the CastPlan-driven bf16 rewrite or
        the calibration-based int8 rewrite, with BN-affine weight folding
        ahead of either.  Tier unset ⇒ the structural triple verbatim
        (byte-identical plans, the PR 7 off-path contract); train plans are
        never tier-rewritten.  Tier pass stats append to
        :meth:`pass_stats`'s eval row."""
        is_train = bool(is_train)
        hit = self._opt_cache.get(is_train)
        if hit is None:
            hit = self._structural_plan(is_train)
            if self._precision_tier and not is_train:
                from . import graph_passes

                tctx = self._tier_context()
                if tctx is None:
                    import warnings

                    warnings.warn(
                        "MXNET_PRECISION_TIER=%s set but this executor has "
                        "unbound inputs — no cast plan, precision tier "
                        "skipped for this plan" % self._precision_tier)
                else:
                    g = graph_passes.Graph(hit[0], hit[1], hit[2])
                    g, rows = graph_passes.precision.apply(
                        g, self._precision_tier, tctx)
                    # kept SEPARATE from the cached structural stats (a
                    # struct-cache hit would otherwise re-append on every
                    # tier change); pass_stats() composes the two
                    self._tier_stats = {"passes": rows,
                                        "nodes_post": g.n_nodes}
                    # quality plane's drift baseline: the sites this
                    # twin actually quantized, keyed to the calibration
                    # table the executable was built from
                    self._int8_sites = dict(tctx.int8_sites)
                    hit = (list(g.entries), list(g.heads),
                           g.constants or None)
            self._opt_cache[is_train] = hit
        return hit

    def _tier_context(self):
        """Build the :class:`graph_passes.precision.TierContext` the tier
        passes consume — the structural-plan CastPlan (the exact artifact
        ``precision_plan(is_train=False)`` returns), bound avals/values,
        and the int8 calibration table.  None when inputs are unbound (a
        cast plan over unknown dtypes would be a guess)."""
        from . import analysis
        from .analysis import numerics as _numerics
        from .graph_passes import precision as _precision

        ctx = analysis.executor_context(self, is_train=False,
                                        plan="structural")
        if not ctx.has_avals:
            return None
        cast_plan = _numerics.precision_plan(ctx)
        return _precision.TierContext(
            cast_plan=cast_plan,
            arg_names=self._arg_names, aux_names=self._aux_names,
            arg_avals=ctx.arg_avals, aux_avals=ctx.aux_avals,
            arg_values={n: a._data for n, a in self.arg_dict.items()},
            aux_values={n: a._data for n, a in self.aux_dict.items()},
            calibration=self._calibration)

    @property
    def precision_tier(self):
        """This executor's precision tier label: ``"bf16"``/``"int8"``, or
        ``"fp32"`` when no tier is active — the warmup-row /
        ``Engine.stats()`` discriminator (ISSUE 15)."""
        return self._precision_tier or "fp32"

    def set_precision_tier(self, tier, calibration=None):
        """Override the bind-time ``MXNET_PRECISION_TIER`` snapshot —
        how ``Predictor.with_precision`` builds explicit twins without
        touching the process environment.  ``tier`` is ``"bf16"``,
        ``"int8"``, or None/``"fp32"`` (clear); ``calibration`` is the
        int8 tier's :class:`~.graph_passes.precision.CalibrationTable`.
        Resets the plan/executable caches, so call it before (or instead
        of re-doing) the first forward."""
        from .graph_passes import precision as _precision

        if tier in (None, "fp32"):
            tier = None
        elif tier not in _precision.VALID_TIERS:
            raise ValueError("unknown precision tier %r (valid: %s)"
                             % (tier, list(_precision.VALID_TIERS)))
        if tier and not self._graph_passes:
            raise ValueError(
                "precision tiers ride on the graph-pass layer — "
                "MXNET_GRAPH_PASSES=0 executors cannot host a %r twin"
                % tier)
        self._precision_tier = tier
        self._calibration = calibration
        self._tier_stats = None
        self._int8_sites = {}  # re-stashed at next lowering (new table)
        self._opt_cache.clear()
        self._fwd_cache.clear()
        self._bwd_cache.clear()

    def pass_stats(self):
        """Per-mode graph-pass results (``{"train"/"eval": stats}``) for
        the modes this executor has lowered so far; empty with passes off.
        On an eval plan a precision tier rewrote (ISSUE 15), the tier
        passes append to the eval row's ``passes`` list and
        ``nodes_post``/``seconds`` reflect the final plan — composed here
        so the cached structural stats are never mutated."""
        out = {m: dict(s) for m, s in self._pass_stats.items()}
        tier = self._tier_stats
        if tier is not None and "eval" in out:
            ev = out["eval"]
            ev["passes"] = list(ev["passes"]) + list(tier["passes"])
            ev["nodes_post"] = tier["nodes_post"]
            ev["seconds"] = round(
                ev["seconds"] + sum(r["seconds"] for r in tier["passes"]), 6)
        return out

    def check(self, is_train=False):
        """Run the registered graph-IR analyzers (``mxnet_tpu.analysis``,
        ISSUE 8) over the plan this executor lowers for ``is_train`` ->
        sorted ``[Diagnostic]`` (most severe first; empty = clean).  Static
        contract checking only — PRNG-stream safety, abstract shape/dtype
        walk, dead inputs/aux — no device work and no compile.  Calling it
        is the opt-in; the ``MXNET_GRAPH_ANALYZERS`` gate only controls the
        automatic serving-warmup surface."""
        from . import analysis

        return analysis.check_executor(self, bool(is_train))

    def precision_plan(self, is_train=False):
        """The fingerprinted cast-plan artifact (ISSUE 11) for the plan
        this executor lowers for ``is_train`` — one ``bf16_safe |
        fp32_accum | fp32_only`` verdict per plan node, from the numerics
        analyzer's dtype-flow + interval + sensitivity analysis
        (``analysis.numerics``; docs/ANALYSIS.md has the verdict table).
        This is the exact contract the precision-tier passes consume
        (``graph_passes/precision.py``, ISSUE 15), so the verdicts are
        computed over the STRUCTURAL plan — the fp32 graph the tier
        rewrites — even on an executor whose tier is active; its
        ``fingerprint()`` changes when and only when the plan or the
        sensitivity/analyzer registry versions change.
        Static (``jax.eval_shape``) — no compile, no device work; raises
        ``ValueError`` on an executor with unbound inputs."""
        from . import analysis

        return analysis.precision_plan_executor(self, bool(is_train))

    def _graph_fn(self, is_train, monitor=None):
        """Pure fn (arg_vals, aux_vals, key) -> (head_vals, new_aux_vals).

        ``monitor``: optional callback(name, jax_value) invoked per node output
        — only used on the un-jitted path (reference ExecuteMonCallback,
        graph_executor.cc:1562).  The monitor path always evaluates the RAW
        captured plan: a debugging hook must see every captured node, not
        the pass-optimized subset.
        """
        from .graph_passes.ir import node_call_attrs
        from .symbol.symbol import _node_input_names

        if monitor is not None:
            plan, heads, const_env = self._plan, self._head_names, None
        else:
            plan, heads, const_env = self._opt_plan(is_train)
        aux_names = list(self._aux_names)
        arg_names = list(self._arg_names)
        # locals only: the returned fn must NOT close over the Executor —
        # the Module fused stepper keeps it across re-binds, and an
        # executor reference would pin the old buffers after reshape
        head_names = list(heads)
        # compile plane (ISSUE 13): under MXNET_COSTPLANE each node's ops
        # trace inside jax.named_scope(node.name), so profiler traces and
        # HLO metadata attribute device time back to symbolic node names.
        # Snapshot at build: the scope is pure trace-time metadata (the
        # jaxpr is unchanged, zero retraces — tested), and with the gate
        # off the eval loop below is byte-identical to a scopeless build.
        from .telemetry import costplane

        if costplane.enabled():
            import jax as _jax

            def run_node(node, args, attrs):
                with _jax.named_scope(node.name):
                    return node.op.fn(*args, **attrs)
        else:
            def run_node(node, args, attrs):
                return node.op.fn(*args, **attrs)

        def fn(arg_vals, aux_vals, key):  # mxlint: traced
            env = dict(const_env) if const_env else {}
            env.update(zip(arg_names, arg_vals))
            env.update(zip(aux_names, aux_vals))
            new_aux = dict(zip(aux_names, aux_vals))
            for node, in_names in plan:
                attrs = node_call_attrs(node, key, is_train)
                args = [env[n] for n in in_names]
                res = run_node(node, args, attrs)
                outs = res if isinstance(res, tuple) else (res,)
                if is_train and node.op.aux_update is not None:
                    by_arg = dict(zip(_node_input_names(node), node.inputs))
                    aux_in = {
                        a: new_aux[by_arg[a].name]
                        for a in node.op.aux
                        if a in by_arg and by_arg[a].is_var and by_arg[a].name in new_aux
                    }
                    updated = node.op.aux_update(attrs, res, aux_in)
                    for a, v in updated.items():
                        new_aux[by_arg[a].name] = v
                if len(outs) > 1 and node.num_outputs == 1:
                    outs = outs[:1]  # hidden outputs (e.g. BatchNorm stats)
                for i, o in enumerate(outs):
                    nm = (
                        "%s_output%d" % (node.name, i)
                        if node.num_outputs > 1
                        else "%s_output" % node.name
                    )
                    env[nm] = o
                    if monitor is not None:
                        monitor(nm, o)
            heads = [env[h] for h in head_names]
            return heads, [new_aux[n] for n in aux_names]

        return fn

    def _tier_key_parts(self, is_train):
        """Extra AOT logical-key parts for an active precision tier (ISSUE
        15): the tier fingerprint (pass names:versions + numerics contract
        versions) and, for calibrated int8 twins, the calibration-table
        fingerprint — so two twins of one checkpoint, or one twin across a
        re-calibration, can never share an executable.  Empty (keys
        byte-identical to pre-tier builds) when no tier is active or for
        train plans, which the tier never rewrites."""
        if not self._precision_tier or is_train:
            return ()
        from .graph_passes import precision as _precision

        parts = ("precision_tier",
                 _precision.tier_fingerprint(self._precision_tier))
        if self._calibration is not None:
            parts += (self._calibration.fingerprint(),)
        return (parts,)

    def _compiled(self, is_train):
        import jax

        if is_train not in self._fwd_cache:
            fn = jax.jit(self._graph_fn(is_train))
            from . import compile_cache

            if compile_cache.active():
                # persistent AOT executable cache (ISSUE 6): per shape
                # signature the forward restores from MXNET_AOT_CACHE
                # instead of trace+lower+XLA-compile; gate off ⇒ the plain
                # jit above, byte-identical to before.  passes_on pins the
                # bind-time graph-pass snapshot into the logical key, so
                # this executor's entries always describe the plan it
                # actually lowered (ISSUE 7).
                fn = compile_cache.CachedFunction(
                    fn,
                    ("executor_fwd",
                     compile_cache.symbol_fingerprint(self._symbol),
                     bool(is_train)) + self._tier_key_parts(is_train),
                    name="executor_fwd", passes_on=self._graph_passes)
            else:
                from .telemetry import costplane

                if costplane.enabled():
                    # compile plane (ISSUE 13): without the AOT cache the
                    # forward is a plain jit whose compiles XLA pays
                    # invisibly — the instrumented split records one
                    # ledger row per shape signature.  Gate off keeps the
                    # plain jit (one env read).
                    fn = costplane.instrument_jit(
                        fn, "executor_fwd",
                        ("executor_fwd",
                         compile_cache.symbol_fingerprint(self._symbol),
                         bool(is_train), self._graph_passes)
                        + self._tier_key_parts(is_train))
            self._fwd_cache[is_train] = fn
        return self._fwd_cache[is_train]

    # -- AOT warmup surface (compile_cache.py, ISSUE 6) ----------------------
    def _aot_example_args(self):
        import jax

        arg_vals = [self.arg_dict[n]._data for n in self._arg_names]
        aux_vals = [self.aux_dict[n]._data for n in self._aux_names]
        # same aval as random.next_key()'s split keys: raw uint32[2]
        return arg_vals, aux_vals, jax.random.PRNGKey(0)

    def aot_lower(self, is_train=False):
        """Stage 1 of the warmup compile split: disk-restore or trace+lower
        this executor's forward for its bound shapes.  Pure host work — safe
        concurrently and off a serving device loop.  → handle for
        :meth:`aot_finalize`, or None when ``MXNET_AOT_CACHE`` is off (or an
        input is unbound; warmup then falls back to first-forward compile)."""
        from . import compile_cache

        fn = self._compiled(bool(is_train))
        if not isinstance(fn, compile_cache.CachedFunction):
            return None
        try:
            args = self._aot_example_args()
        except KeyError:
            return None
        return fn.lower_prepare(*args)

    def aot_finalize(self, handle, is_train=False):
        """Stage 2: XLA-compile (or pass through a disk-restored) handle and
        install the executable, so the next forward on these shapes
        dispatches without compiling.  → the finalize row."""
        return self._compiled(bool(is_train)).finalize(handle)

    # -- API ----------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """Run forward (reference GraphExecutor::Forward → RunOps)."""
        from . import random as _rnd

        for k, v in kwargs.items():
            if k not in self._arg_names:
                raise MXNetError(
                    "forward() got unknown argument %r; expected one of %s" % (k, self._arg_names)
                )
            self.arg_dict[k] = v if isinstance(v, NDArray) else array(v)
        missing = [n for n in self._arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError("forward() missing bound values for arguments: %s" % missing)
        arg_vals = [self.arg_dict[n]._data for n in self._arg_names]
        aux_vals = [self.aux_dict[n]._data for n in self._aux_names]
        key = _rnd.next_key()
        from . import profiler as _prof

        _pt0 = _prof._now_us() if _prof._symbolic_profiling_active() else None
        if self._monitor is not None:
            cb = self._monitor
            if self._monitor_all:
                # reference monitor_all=True also reports every node INPUT
                # (graph_executor.cc ExecuteMonCallback input loop) — for a
                # flat executor that is the arg/aux arrays themselves
                for n in self._arg_names:
                    cb(n, self.arg_dict[n])
                for n in self._aux_names:
                    cb(n, self.aux_dict[n])
            heads, new_aux = self._graph_fn(
                bool(is_train), monitor=lambda n, v: cb(n, _wrap(v))
            )(arg_vals, aux_vals, key)
        else:
            heads, new_aux = self._compiled(bool(is_train))(arg_vals, aux_vals, key)
        for n, v in zip(self._aux_names, new_aux):
            self.aux_dict[n]._rebind(v)
        self.outputs = [_wrap(h) for h in heads]
        self._last_key = key
        self._last_is_train = bool(is_train)
        if self._last_is_train:
            # train-step dispatch accounting (ISSUE 3 regression surface):
            # counted here at the dispatch site so manual loops and
            # BucketingModule report the same 2+P as Module.forward_backward
            from . import telemetry

            telemetry.note_dispatch(1, path="legacy")
        if _pt0 is not None:
            # duration = trace+enqueue (async dispatch), same caveat as the
            # eager per-op events; the XLA device timeline is use_xla_trace
            _prof._emit_op("Executor::Forward", _pt0, _prof._now_us() - _pt0)
        return self.outputs

    def backward(self, out_grads=None, is_train=None):
        """Gradients into grad arrays per grad_req (reference
        GraphExecutor::Backward; the Gradient pass is jax.vjp here).

        ``is_train=None`` (default) differentiates in the mode the last
        forward ran in; passing an explicit bool overrides it."""
        import jax
        import jax.numpy as jnp

        diff_names = tuple(
            n for n in self._arg_names if self._grad_req.get(n, "null") != "null" and n in self.grad_dict
        )
        if not diff_names:
            return
        from . import profiler as _prof

        _pt0 = _prof._now_us() if _prof._symbolic_profiling_active() else None
        aux_vals = [self.aux_dict[n]._data for n in self._aux_names]
        key = getattr(self, "_last_key", None)
        if key is None:
            from . import random as _rnd

            key = _rnd.next_key()
        arg_vals = [self.arg_dict[n]._data for n in self._arg_names]
        ones_ct = out_grads is None
        if not ones_ct:
            if isinstance(out_grads, (NDArray, np.ndarray)):
                out_grads = [out_grads]
            cts_in = [g._data if isinstance(g, NDArray) else jnp.asarray(g) for g in out_grads]
        # differentiate in the mode the last forward actually ran in — a
        # backward after forward(is_train=False) must see eval-mode
        # BatchNorm/Dropout, not a silently re-traced train graph
        if is_train is None:
            mode = getattr(self, "_last_is_train", True)
        else:
            mode = bool(is_train)
        cache_key = (diff_names, ones_ct, mode)
        bwd_fn = self._bwd_cache.get(cache_key)
        if bwd_fn is None:
            fn = self._graph_fn(mode)
            arg_names = list(self._arg_names)
            dset = set(diff_names)
            const_names = [n for n in arg_names if n not in dset]

            def bwd(diff_vals, const_vals, aux_v, k, cts):
                def f(dvals):
                    merged = dict(zip(const_names, const_vals))
                    merged.update(zip(diff_names, dvals))
                    heads, _ = fn([merged[n] for n in arg_names], aux_v, k)
                    return heads

                heads, vjp_fn = jax.vjp(f, diff_vals)
                c = [jnp.ones_like(h) for h in heads] if ones_ct else cts
                (grads,) = vjp_fn(c)
                return grads

            bwd_fn = self._bwd_cache[cache_key] = jax.jit(bwd)
        dset = set(diff_names)
        grads = bwd_fn(
            [self.arg_dict[n]._data for n in diff_names],
            [v for n, v in zip(self._arg_names, arg_vals) if n not in dset],
            aux_vals,
            key,
            [] if ones_ct else cts_in,
        )
        for n, g in zip(diff_names, grads):
            req = self._grad_req.get(n, "write")
            tgt = self.grad_dict.get(n)
            if tgt is None:
                continue
            if req == "add":
                tgt._rebind(tgt._data + g)
            else:
                tgt._rebind(g)
        from . import telemetry

        telemetry.note_dispatch(1, path="legacy")
        if _pt0 is not None:
            _prof._emit_op("Executor::Backward", _pt0,
                           _prof._now_us() - _pt0)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new shapes (reference GraphExecutor::Reshape:1053).

        jit recompiles per shape signature automatically; only arrays need
        re-allocation here.
        """
        from .ndarray import zeros as nd_zeros

        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for n, s in zip(self._arg_names, arg_shapes):
            old = self.arg_dict.get(n)
            if old is not None and tuple(old.shape) == tuple(s):
                new_args[n] = old
            else:
                new_args[n] = nd_zeros(s, ctx=self._ctx)
        new_grads = None
        if self.grad_dict:
            new_grads = {}
            for n, s in zip(self._arg_names, arg_shapes):
                if n in self.grad_dict:
                    old = self.grad_dict[n]
                    new_grads[n] = old if tuple(old.shape) == tuple(s) else nd_zeros(s, ctx=self._ctx)
        new_aux = {}
        for n, s in zip(self._aux_names, aux_shapes):
            old = self.aux_dict.get(n)
            new_aux[n] = old if old is not None and tuple(old.shape) == tuple(s) else nd_zeros(s, ctx=self._ctx)
        return Executor(self._symbol, self._ctx, new_args, new_grads, self._grad_req, new_aux)

    def copy_params_from(self, arg_params, aux_params=None, allow_extra_params=False):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._rebind(v._data if isinstance(v, NDArray) else array(v)._data)
            elif not allow_extra_params:
                raise MXNetError("unknown arg %s" % k)
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._rebind(v._data if isinstance(v, NDArray) else array(v)._data)
            elif not allow_extra_params:
                raise MXNetError("unknown aux %s" % k)

    def set_monitor_callback(self, callback, monitor_all=False):
        """Install per-output inspection (reference executor.h:172 monitor).
        Forward runs un-jitted — and over the RAW captured plan, graph
        passes bypassed — while a monitor is installed, so the callback
        sees every captured node; monitor_all additionally reports node
        inputs (args/aux — weights included)."""
        self._monitor = callback
        self._monitor_all = bool(monitor_all)

    @property
    def output_dict(self):
        return dict(zip(self._out_names, self.outputs))

    def debug_str(self):
        return self._symbol.debug_str()


def _simple_bind_for_test(sym, locations, aux_states=None, ctx=None, grad_req="null"):
    """Bind with concrete numpy/NDArray inputs (test_utils helper)."""
    args = {}
    if isinstance(locations, dict):
        for k, v in locations.items():
            args[k] = v if isinstance(v, NDArray) else array(v)
    else:
        for n, v in zip(sym.list_arguments(), locations):
            args[n] = v if isinstance(v, NDArray) else array(v)
    aux = {}
    if aux_states:
        if isinstance(aux_states, dict):
            aux = {k: (v if isinstance(v, NDArray) else array(v)) for k, v in aux_states.items()}
        else:
            aux = {
                n: (v if isinstance(v, NDArray) else array(v))
                for n, v in zip(sym.list_auxiliary_states(), aux_states)
            }
    # fill any remaining args (params) with zeros via shape inference
    known = {k: tuple(v.shape) for k, v in args.items()}
    try:
        arg_shapes, _, aux_shapes = sym.infer_shape(**known)
        from .ndarray import zeros as nd_zeros

        for n, s in zip(sym.list_arguments(), arg_shapes):
            if n not in args and s is not None:
                args[n] = nd_zeros(s, ctx=ctx)
        for n, s in zip(sym.list_auxiliary_states(), aux_shapes):
            if n not in aux and s is not None:
                aux[n] = nd_zeros(s, ctx=ctx)
    except MXNetError:
        pass
    grads = {n: None for n in args}
    if grad_req != "null":
        from .ndarray import zeros as nd_zeros

        grads = {n: nd_zeros(a.shape, ctx=ctx) for n, a in args.items()}
    return Executor(sym, ctx, args, grads if grad_req != "null" else None, grad_req, aux)
