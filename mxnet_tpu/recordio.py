"""RecordIO format — reference ``python/mxnet/recordio.py`` (MXRecordIO,
MXIndexedRecordIO, IRHeader/pack/unpack :291-367, pack_img/unpack_img) and the
dmlc-core recordio framing used by ``src/io/``.

On-disk framing: ``[magic:u32le][lrec:u32le][payload, 4B-padded]`` with
``lrec = (cflag<<29)|len``; payloads containing the magic word are split into
continuation chunks (cflag 1/2/3) — identical to the reference so .rec files
round-trip.  The hot path goes through the native C++ library
(``src/io/recordio.cc`` here); a pure-Python implementation is the fallback.
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct
from collections import namedtuple

import numpy as np

from . import _native

__all__ = [
    "MXRecordIO",
    "MXIndexedRecordIO",
    "IRHeader",
    "pack",
    "unpack",
    "pack_img",
    "unpack_img",
]

_KMAGIC = 0xCED7230A
_MAGIC_BYTES = struct.pack("<I", _KMAGIC)


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


class _PyWriter:
    def __init__(self, path):
        self._f = open(path, "wb")

    def tell(self):
        return self._f.tell()

    def write(self, data):
        if len(data) >= (1 << 29):
            raise ValueError("record too large: %d bytes (max 2^29-1)" % len(data))
        start = self._f.tell()
        # Split payload at embedded magic words (dmlc recordio scheme).
        chunks = data.split(_MAGIC_BYTES)
        n = len(chunks)
        for i, chunk in enumerate(chunks):
            if n == 1:
                cflag = 0
            elif i == 0:
                cflag = 1
            elif i == n - 1:
                cflag = 3
            else:
                cflag = 2
            self._f.write(_MAGIC_BYTES)
            self._f.write(struct.pack("<I", _encode_lrec(cflag, len(chunk))))
            self._f.write(chunk)
            pad = (4 - (len(chunk) & 3)) & 3
            if pad:
                self._f.write(b"\x00" * pad)
        return start

    def close(self):
        self._f.close()


class _PyReader:
    def __init__(self, path):
        self._f = open(path, "rb")

    def tell(self):
        return self._f.tell()

    def seek(self, pos):
        self._f.seek(pos)

    def read(self):
        out = []
        cont = False
        while True:
            head = self._f.read(8)
            if len(head) < 8:
                return None
            magic, lrec = struct.unpack("<II", head)
            if magic != _KMAGIC:
                return None
            cflag, length = lrec >> 29, lrec & ((1 << 29) - 1)
            if cont:
                out.append(_MAGIC_BYTES)
            chunk = self._f.read(length)
            if len(chunk) < length:
                return None
            out.append(chunk)
            pad = (4 - (length & 3)) & 3
            if pad:
                self._f.seek(pad, os.SEEK_CUR)
            if cflag in (0, 3):
                return b"".join(out)
            cont = True

    def close(self):
        self._f.close()


class _NativeWriter:
    def __init__(self, path):
        self._lib = _native.lib()
        self._h = self._lib.MXTRecordIOWriterCreate(path.encode())
        if not self._h:
            raise IOError("cannot open %s for writing" % path)

    def tell(self):
        return self._lib.MXTRecordIOWriterTell(self._h)

    def write(self, data):
        if len(data) >= (1 << 29):
            raise ValueError("record too large: %d bytes (max 2^29-1)" % len(data))
        return self._lib.MXTRecordIOWriterWrite(self._h, data, len(data))

    def close(self):
        if self._h:
            self._lib.MXTRecordIOWriterFree(self._h)
            self._h = None


class _NativeReader:
    def __init__(self, path):
        self._lib = _native.lib()
        self._h = self._lib.MXTRecordIOReaderCreate(path.encode())
        if not self._h:
            raise IOError("cannot open %s for reading" % path)

    def tell(self):
        return self._lib.MXTRecordIOReaderTell(self._h)

    def seek(self, pos):
        self._lib.MXTRecordIOReaderSeek(self._h, pos)

    def read(self):
        n = ctypes.c_uint64()
        ptr = ctypes.c_char_p()
        ok = self._lib.MXTRecordIOReaderNext(self._h, ctypes.byref(ptr), ctypes.byref(n))
        if not ok:
            return None
        return ctypes.string_at(ptr, n.value)

    def close(self):
        if self._h:
            self._lib.MXTRecordIOReaderFree(self._h)
            self._h = None


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference recordio.py:36)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.is_open = False
        self.open()

    def open(self):
        native = _native.lib() is not None
        if self.flag == "w":
            self._impl = _NativeWriter(self.uri) if native else _PyWriter(self.uri)
            self.writable = True
        elif self.flag == "r":
            self._impl = _NativeReader(self.uri) if native else _PyReader(self.uri)
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def __del__(self):
        self.close()

    def __getstate__(self):
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        del d["_impl"]
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        is_open = d["is_open"]
        self.is_open = False
        if is_open:
            self.open()

    def close(self):
        if not self.is_open:
            return
        self._impl.close()
        self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        if isinstance(buf, str):
            buf = buf.encode()
        self._impl.write(buf)

    def read(self):
        assert not self.writable
        out = self._impl.read()
        return out

    def tell(self):
        return self._impl.tell()


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with a key→offset index sidecar (reference recordio.py:180)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write("%s\t%d\n" % (str(key), self.idx[key]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        self._impl.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        assert self.writable
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Packs header+payload into an image-record string (reference :309)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    s = struct.pack(_IR_FORMAT, *header) + s
    return s


def unpack(s):
    """Inverse of pack (reference :344)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        header = header._replace(label=np.frombuffer(s, np.float32, header.flag))
        s = s[header.flag * 4 :]
    return header, s


def _encode_jpeg(img, quality=95):
    from io import BytesIO

    from PIL import Image

    buf = BytesIO()
    Image.fromarray(img).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def _decode_image(s):
    lib = _native.lib()
    if lib is not None and s[:2] == b"\xff\xd8":  # JPEG magic
        cap = len(s) * 64 + (1 << 16)
        out = np.empty(cap, dtype=np.uint8)
        w = ctypes.c_int()
        h = ctypes.c_int()
        c = ctypes.c_int()
        src = np.frombuffer(s, dtype=np.uint8)
        rc = lib.MXTDecodeJPEG(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            len(s),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            cap,
            ctypes.byref(w),
            ctypes.byref(h),
            ctypes.byref(c),
        )
        if rc == 0:
            return out[: w.value * h.value * c.value].reshape(h.value, w.value, c.value).copy()
    from io import BytesIO

    from PIL import Image

    return np.asarray(Image.open(BytesIO(s)).convert("RGB"))


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Packs an image array into an image-record string (reference :386)."""
    if img_fmt.lower() not in (".jpg", ".jpeg"):
        raise ValueError("only JPEG packing is supported (got %s)" % img_fmt)
    img = np.asarray(img, dtype=np.uint8)
    return pack(header, _encode_jpeg(img, quality=quality))


def unpack_img(s, iscolor=-1):
    """Unpacks an image-record string to (header, HWC uint8 array)."""
    header, s = unpack(s)
    img = _decode_image(s)
    if iscolor == 0 and img.ndim == 3:
        img = np.asarray(
            0.299 * img[..., 0] + 0.587 * img[..., 1] + 0.114 * img[..., 2], dtype=np.uint8
        )
    return header, img
