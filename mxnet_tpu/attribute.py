"""Attribute scoping support (reference ``python/mxnet/attribute.py``).

``AttrScope`` lives in :mod:`mxnet_tpu.base`; this module keeps the
reference's import path (``mx.attribute.AttrScope``).
"""
from .base import AttrScope

__all__ = ["AttrScope"]
