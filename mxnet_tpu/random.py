"""Global RNG state — reference ``python/mxnet/random.py`` (mx.random.seed).

Eager random ops draw fresh counter-based PRNG keys from this module, giving
MXNet's stateful-looking API on top of JAX's functional RNG.  Per-device seed
streams (the reference seeds each device's Random resource separately,
src/resource.cc) correspond to folding the device ordinal into the key.
"""
from __future__ import annotations

import threading

__all__ = ["seed", "next_key", "current_seed", "key_provider",
           "uniform", "normal", "randn", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial", "randint",
           "multinomial", "shuffle"]

_state = threading.local()


def _ensure():
    if not getattr(_state, "init", False):
        import jax

        _state.key = jax.random.PRNGKey(0)
        _state.seed_val = 0
        _state.provider = None
        _state.init = True


def seed(seed_state, ctx="all"):
    """Seed the global generator (reference random.py:seed).  ctx kept for API parity."""
    import jax

    _ensure()
    _state.key = jax.random.PRNGKey(int(seed_state))
    _state.seed_val = int(seed_state)


def current_seed():
    _ensure()
    return _state.seed_val


def next_key():
    """Split off a fresh key (called by the nd frontend per random op).

    Inside a :class:`key_provider` scope (hybridized/jitted graph capture),
    keys instead come from the provider so randomness is a *traced input* of
    the compiled graph rather than a constant baked at trace time.
    """
    import jax

    _ensure()
    if _state.provider is not None:
        return _state.provider()
    _state.key, sub = jax.random.split(_state.key)
    return sub


class key_provider:
    """Scope that makes :func:`next_key` derive keys from a base key by
    fold-in counter — used when tracing a CachedOp-style graph so the same
    trace re-executes with fresh randomness each call."""

    def __init__(self, base_key):
        self._base = base_key
        self._n = 0
        self._prev = None

    def __call__(self):
        import jax

        k = jax.random.fold_in(self._base, self._n)
        self._n += 1
        return k

    def __enter__(self):
        _ensure()
        self._prev = _state.provider
        _state.provider = self
        return self

    def __exit__(self, *a):
        _state.provider = self._prev


# ---------------------------------------------------------------------------
# module-level samplers (reference python/mxnet/random.py re-exports the
# nd.random generators at mx.random.*; randn is the positional-shape variant
# of normal, random.py:126)
# ---------------------------------------------------------------------------

def _delegate(name):
    def f(*args, **kwargs):
        from .ndarray import random as _ndrandom

        return getattr(_ndrandom, name)(*args, **kwargs)

    f.__name__ = name
    f.__doc__ = "mx.random.%s — see nd.random.%s (reference random.py)." % (
        name, name)
    return f


uniform = _delegate("uniform")
normal = _delegate("normal")
gamma = _delegate("gamma")
exponential = _delegate("exponential")
poisson = _delegate("poisson")
negative_binomial = _delegate("negative_binomial")
generalized_negative_binomial = _delegate("generalized_negative_binomial")
randint = _delegate("randint")
multinomial = _delegate("multinomial")
shuffle = _delegate("shuffle")


def randn(*shape, loc=0.0, scale=1.0, dtype="float32"):
    """Standard-normal sample with positional dims (reference random.py randn)."""
    from .ndarray import random as _ndrandom

    return _ndrandom.normal(loc, scale, tuple(shape) or (1,), dtype)
