"""Global RNG state — reference ``python/mxnet/random.py`` (mx.random.seed).

Eager random ops draw fresh counter-based PRNG keys from this module, giving
MXNet's stateful-looking API on top of JAX's functional RNG.  Per-device seed
streams (the reference seeds each device's Random resource separately,
src/resource.cc) correspond to folding the device ordinal into the key.
"""
from __future__ import annotations

import threading

__all__ = ["seed", "next_key", "current_seed"]

_state = threading.local()


def _ensure():
    if not getattr(_state, "init", False):
        import jax

        _state.key = jax.random.PRNGKey(0)
        _state.seed_val = 0
        _state.init = True


def seed(seed_state, ctx="all"):
    """Seed the global generator (reference random.py:seed).  ctx kept for API parity."""
    import jax

    _ensure()
    _state.key = jax.random.PRNGKey(int(seed_state))
    _state.seed_val = int(seed_state)


def current_seed():
    _ensure()
    return _state.seed_val


def next_key():
    """Split off a fresh key (called by the nd frontend per random op)."""
    import jax

    _ensure()
    _state.key, sub = jax.random.split(_state.key)
    return sub
