"""Test harness — TPU-native port of reference ``python/mxnet/test_utils.py``.

Same testing philosophy as the reference (SURVEY §4): numpy oracles,
dtype-aware tolerance tables (test_utils.py:470), finite-difference gradient
checks (:792), symbolic fwd/bwd checks (:925, :999), and cross-backend
``check_consistency`` (:1207) — here CPU-vs-TPU instead of CPU-vs-GPU.
"""
from __future__ import annotations

import numpy as np

from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array

_rng = np.random.RandomState(1234)

# dtype-aware default tolerances (reference test_utils.py:470 table)
_DEFAULT_RTOL = {
    np.dtype(np.float16): 1e-2,
    np.dtype(np.float32): 1e-4,
    np.dtype(np.float64): 1e-5,
    np.dtype(np.bool_): 0,
    np.dtype(np.int8): 0,
    np.dtype(np.uint8): 0,
    np.dtype(np.int32): 0,
    np.dtype(np.int64): 0,
}
_DEFAULT_ATOL = {
    np.dtype(np.float16): 1e-1,
    np.dtype(np.float32): 1e-3,
    np.dtype(np.float64): 1e-20,
    np.dtype(np.bool_): 0,
    np.dtype(np.int8): 0,
    np.dtype(np.uint8): 0,
    np.dtype(np.int32): 0,
    np.dtype(np.int64): 0,
}


def default_context():
    """Context under test; switched by env like the reference (test_utils.py:53)."""
    import os

    dev = os.environ.get("MXNET_TEST_DEVICE", "")
    if dev.startswith("tpu") or dev.startswith("gpu"):
        from .context import tpu

        return tpu(0)
    return current_context()


def default_dtype():
    return np.float32


def get_atol(atol=None, dtype=np.dtype(np.float64)):
    return _DEFAULT_ATOL[np.dtype(dtype)] if atol is None else atol


def get_rtol(rtol=None, dtype=np.dtype(np.float64)):
    return _DEFAULT_RTOL[np.dtype(dtype)] if rtol is None else rtol


def _as_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def same(a, b):
    return np.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a, b = _as_np(a), _as_np(b)
    ct = np.promote_types(a.dtype, b.dtype)
    return np.allclose(a, b, get_rtol(rtol, ct), get_atol(atol, ct), equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"), equal_nan=False):
    """Elementwise closeness with the reference's relative-error report
    (reference test_utils.py:470)."""
    a, b = _as_np(a), _as_np(b)
    ct = np.promote_types(a.dtype, b.dtype)
    rtol, atol = get_rtol(rtol, ct), get_atol(atol, ct)
    if np.allclose(a, b, rtol, atol, equal_nan):
        return
    denom = np.abs(a) + np.abs(b) + atol
    rel = np.abs(a - b) / denom
    idx = np.unravel_index(np.argmax(rel), rel.shape)
    raise AssertionError(
        "Error %f exceeds tolerance rtol=%e, atol=%e (max at %s: %s=%s, %s=%s)\n%s vs %s"
        % (rel[idx], rtol, atol, idx, names[0], a[idx], names[1], b[idx], a.flatten()[:10], b.flatten()[:10])
    )


def rand_shape_nd(ndim, dim=10):
    return tuple(_rng.randint(1, dim + 1, size=ndim))


def rand_shape_2d(dim0=10, dim1=10):
    return _rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1)


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return _rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1), _rng.randint(1, dim2 + 1)


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    """Random NDArray (reference test_utils.py:339).  Sparse stypes return the
    BCOO-backed sparse types when requested."""
    dtype = dtype or np.float32
    data = _rng.uniform(-1.0, 1.0, size=shape).astype(dtype)
    if stype == "default":
        return array(data, ctx=ctx)
    from .ndarray import sparse

    if density is not None:
        mask = _rng.uniform(0, 1, size=shape) < density
        data = data * mask
    return sparse.cast_storage(array(data, ctx=ctx), stype=stype)


def random_arrays(*shapes):
    arrays = [np.array(_rng.randn(), dtype=np.float64) if len(s) == 0 else _rng.randn(*s).astype(np.float64) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def check_numeric_gradient(
    f,
    locations,
    grads=None,
    rtol=1e-2,
    atol=None,
    eps=1e-4,
    dtype=np.float64,
):
    """Finite-difference check of an NDArray function's autograd gradients
    (reference test_utils.py:792 — here against the autograd tape instead of
    executor backward).

    f: callable taking NDArrays and returning one NDArray (scalar-reduced
    internally if not already scalar).
    locations: list of numpy arrays (the differentiable inputs).
    """
    from . import autograd
    from .ndarray import ones as nd_ones

    nd_inputs = [array(loc.astype(np.float32)) for loc in locations]
    for x in nd_inputs:
        x.attach_grad()
    with autograd.record():
        out = f(*nd_inputs)
        loss = out.sum() if out.size != 1 else out
    loss.backward()
    sym_grads = [x.grad.asnumpy().astype(np.float64) for x in nd_inputs]

    # numeric gradients via central differences on numpy copies
    for gi, loc in enumerate(locations):
        if grads is not None and gi not in grads:
            continue
        num_grad = np.zeros_like(loc, dtype=np.float64)
        flat = loc.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            plus = float(
                f(*[array(l.astype(np.float32)) for l in locations]).sum().asscalar()
            )
            flat[i] = orig - eps
            minus = float(
                f(*[array(l.astype(np.float32)) for l in locations]).sum().asscalar()
            )
            flat[i] = orig
            num_grad.reshape(-1)[i] = (plus - minus) / (2 * eps)
        assert_almost_equal(
            num_grad,
            sym_grads[gi],
            rtol=rtol,
            atol=atol if atol is not None else 1e-3,
            names=("numeric_grad_%d" % gi, "autograd_%d" % gi),
        )


def check_symbolic_forward(sym, locations, expected, rtol=1e-4, atol=1e-5, aux_states=None, ctx=None):
    """Bind a Symbol, run forward, compare to expected numpy (reference :925)."""
    from .executor import _simple_bind_for_test

    exe = _simple_bind_for_test(sym, locations, aux_states=aux_states, ctx=ctx)
    outs = exe.forward(is_train=False)
    for o, e in zip(outs, expected):
        assert_almost_equal(o.asnumpy(), e, rtol=rtol, atol=atol)
    return [o.asnumpy() for o in outs]


def check_symbolic_backward(sym, locations, out_grads, expected, rtol=1e-4, atol=1e-5, aux_states=None, ctx=None):
    """Run backward, compare input grads to expected numpy (reference :999)."""
    from .executor import _simple_bind_for_test

    exe = _simple_bind_for_test(sym, locations, aux_states=aux_states, ctx=ctx, grad_req="write")
    exe.forward(is_train=True)
    exe.backward(out_grads=[array(g) for g in out_grads])
    grads = {k: v.asnumpy() for k, v in zip(sym.list_arguments(), exe.grad_arrays) if v is not None}
    if isinstance(expected, dict):
        for name, e in expected.items():
            assert_almost_equal(grads[name], e, rtol=rtol, atol=atol, names=("grad_" + name, "expected"))
    else:
        for (name, g), e in zip(sorted(grads.items()), expected):
            assert_almost_equal(g, e, rtol=rtol, atol=atol)
    return grads


def check_consistency(f, inputs, ctx_list=None, rtol=None, atol=None):
    """Run the same computation on each context and cross-compare
    (reference test_utils.py:1207 — CPU vs TPU instead of CPU vs GPU)."""
    ctx_list = ctx_list or [cpu(0), default_context()]
    results = []
    for ctx in ctx_list:
        nd_in = [array(x, ctx=ctx) for x in inputs]
        out = f(*nd_in)
        results.append(out.asnumpy() if isinstance(out, NDArray) else [o.asnumpy() for o in out])
    base = results[0]
    for r in results[1:]:
        if isinstance(base, list):
            for a, b in zip(base, r):
                assert_almost_equal(a, b, rtol=rtol, atol=atol)
        else:
            assert_almost_equal(base, r, rtol=rtol, atol=atol)
    return results


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    from .executor import _simple_bind_for_test

    exe = _simple_bind_for_test(sym, inputs, ctx=ctx)
    outputs = exe.forward(is_train=is_train)
    if len(outputs) == 1:
        return outputs[0].asnumpy()
    return [o.asnumpy() for o in outputs]


def discard_stderr(fn):
    return fn


def load_module_by_path(path, name=None):
    """Import a python file by explicit path, bypassing sys.path.

    Several example families reuse file names (two ``train_fused.py``, two
    ``metric.py``), so ``sys.path``-based imports silently grab whichever
    directory was prepended last — tests and cross-example imports load by
    path instead.
    """
    import importlib.util
    import os
    import sys

    if name is None:
        name = "_bypath_" + os.path.abspath(path).strip(os.sep).replace(
            os.sep, "_").replace("-", "_").replace(".", "_")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(name, None)  # never leave a half-initialized entry
        raise
    return mod


def tiny_mlp_checkpoint(in_dim=8, num_hidden=16, num_classes=4, seed=0):
    """(symbol, params) for the canonical tiny softmax MLP used by the
    serving tests and ``tools/loadgen.py`` — ONE definition so the Engine
    fixture and the load generator cannot drift apart.  Params are seeded
    random NDArrays; no files involved."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=num_classes, name="fc2")
    sym = mx.sym.SoftmaxOutput(fc2, name="softmax")
    exe = sym.simple_bind(grad_req="null", data=(2, in_dim))
    rng = np.random.RandomState(seed)
    params = {n: nd.array(rng.randn(*a.shape).astype(np.float32))
              for n, a in exe.arg_dict.items()
              if n not in ("data", "softmax_label")}
    return sym, params


def deploy_twin_checkpoint(batch=16, image=32, seed=0):
    """(symbol, params, input_shapes) for the two-head deploy-twin graph —
    the ``MXNET_BENCH=predictor`` benchmark topology (conv+BN trunk, then a
    classifier head AND an embedding head, each re-deriving the pooled
    trunk features through a shared helper, so the captured graph carries
    the duplicated subexpressions CSE merges and the eval-dead dropout the
    inference rewrite drops).  ONE definition shared by ``bench.py``,
    ``ci/check_numerics.py`` and the numerics tests, so the acceptance
    surface and the benchmark can never drift apart (ISSUE 11)."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    data = mx.sym.var("data")
    h = data
    for i, nf in enumerate((16, 32)):
        h = mx.sym.Convolution(h, name="conv%d" % i, kernel=(3, 3),
                               num_filter=nf, pad=(1, 1))
        h = mx.sym.BatchNorm(h, name="bn%d" % i, fix_gamma=False)
        h = mx.sym.Activation(h, name="act%d" % i, act_type="relu")
        h = mx.sym.Pooling(h, name="pool%d" % i, kernel=(2, 2),
                           stride=(2, 2), pool_type="max")

    def pooled_features(trunk):
        # per-head feature derivation (auto-named: each call captures a
        # fresh chain — exactly the duplication CSE exists to merge)
        p = mx.sym.Pooling(trunk, kernel=(1, 1), global_pool=True,
                           pool_type="avg")
        return mx.sym.L2Normalization(mx.sym.Flatten(p))

    emb = pooled_features(h)  # embedding head (served for similarity)
    cls = mx.sym.Dropout(pooled_features(h), p=0.5)
    prob = mx.sym.softmax(
        mx.sym.FullyConnected(cls, name="fc2", num_hidden=10), name="prob")
    sym = mx.sym.Group([prob, emb])

    rng = np.random.RandomState(seed)
    input_shapes = {"data": (batch, 3, image, image)}
    arg_shapes, _, aux_shapes = sym.infer_shape(**input_shapes)
    params = {}
    for n, s in zip(sym.list_arguments(), arg_shapes):
        if n != "data":
            params["arg:" + n] = nd.array(
                rng.randn(*s).astype(np.float32) * 0.05)
    for n, s in zip(sym.list_auxiliary_states(), aux_shapes):
        params["aux:" + n] = nd.array(
            np.ones(s, np.float32) if n.endswith("_var")
            else np.zeros(s, np.float32))
    return sym, params, input_shapes
