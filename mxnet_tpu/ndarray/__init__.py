"""mx.nd — imperative operator frontend.

Generated-from-registry op namespace, mirroring reference
``python/mxnet/ndarray/register.py:29,156`` (which code-gens a Python function
per C++ op).  Here the registry holds pure jax functions; the wrapper unwraps
NDArrays, injects RNG keys / train-mode flags, executes eagerly (JAX async
dispatch ≡ engine push), wraps outputs, and tapes the call for autograd
(Imperative::Invoke + RecordOp, reference imperative.cc:87,183).
"""
from __future__ import annotations

import sys
import types

import numpy as np

from ..base import parse_attr, dtype_np
from ..context import current_context, Context
from .. import profiler as _prof
from ..ops import registry as _registry
from ..ops import _load_all  # noqa: F401  (populates the registry)
from .ndarray import NDArray, array, empty, concatenate, waitall, _wrap, _to_device

__all__ = [
    "NDArray",
    "array",
    "empty",
    "zeros",
    "ones",
    "full",
    "arange",
    "concatenate",
    "waitall",
    "save",
    "load",
    "op",
    "random",
]

# attrs that only make sense engine-side in the reference; accepted and ignored
_IGNORED_ATTRS = frozenset({"name", "attr", "__layout__", "cudnn_tune", "cudnn_off", "workspace"})

# ops whose tuple return is partially hidden unless an attr asks for it
_VISIBLE_RULES = {
    "BatchNorm": lambda attrs: 3 if attrs.get("output_mean_var") else 1,
    "LayerNorm": lambda attrs: 3 if attrs.get("output_mean_var") else 1,
    "_sample_multinomial": lambda attrs: 2 if attrs.get("get_prob") else 1,
    "RNN": lambda attrs: (
        (3 if attrs.get("mode", "lstm") == "lstm" else 2) if attrs.get("state_outputs") else 1
    ),
}


def _tape_if_recording(fn, nd_inputs, jargs, attrs, nd_outputs):
    from .. import autograd

    if autograd.is_recording():
        autograd._record_op(fn, nd_inputs, jargs, attrs, nd_outputs)


def _invoke_raw(fn, nd_args, attrs, visible=None, ctx=None):
    """Execute a pure fn on NDArray args: unwrap → run → wrap → tape."""
    jargs = []
    nd_inputs = []
    for a in nd_args:
        if isinstance(a, NDArray):
            jargs.append(a._data)
            nd_inputs.append(a)
        else:
            jargs.append(a)
            nd_inputs.append(None)
    if _prof._op_profiling_active():
        t0 = _prof._now_us()
        res = fn(*jargs, **attrs)
        _prof._emit_op(getattr(fn, "__name__", "op"), t0, _prof._now_us() - t0)
    else:
        res = fn(*jargs, **attrs)
    multi = isinstance(res, tuple)
    outs = res if multi else (res,)
    if ctx is not None:
        outs = tuple(_to_device(o, ctx) for o in outs)
    nd_outs = [_wrap(o, ctx) for o in outs]
    _tape_if_recording(fn, nd_inputs, jargs, attrs, nd_outs)
    if not multi:
        return nd_outs[0]
    if visible is not None:
        nd_outs = nd_outs[:visible]
    return nd_outs[0] if len(nd_outs) == 1 else nd_outs


def _invoke(opdef, args, kwargs):
    kwargs = dict(kwargs)
    out_arr = kwargs.pop("out", None)
    ctx = kwargs.pop("ctx", None)
    for k in list(kwargs):
        if k in _IGNORED_ATTRS:
            kwargs.pop(k)
    args = list(args)
    # map named tensor args to positions
    if not opdef.variadic and opdef.arg_names:
        if len(args) > len(opdef.arg_names):
            # extra positional args are attrs passed positionally, MXNet-style
            # (e.g. nd.clip(x, a_min, a_max)); an extra NDArray is a real
            # arity error, not an attr
            extras = args[len(opdef.arg_names) :]
            args = args[: len(opdef.arg_names)]
            free_attrs = [a for a in opdef.attr_names if a not in kwargs]
            if len(extras) > len(free_attrs) or any(
                isinstance(e, NDArray) or getattr(e, "ndim", 0) > 0 for e in extras
            ):
                raise TypeError(
                    "%s takes at most %d tensor arguments (%d given)"
                    % (opdef.name, len(opdef.arg_names), len(args) + len(extras))
                )
            for a, v in zip(free_attrs, extras):
                kwargs[a] = v
        named = {}
        for i, a in enumerate(args):
            named[opdef.arg_names[i]] = a
        for an in opdef.arg_names:
            if an in kwargs:
                named[an] = kwargs.pop(an)
        args = [named.get(an, opdef.defaults.get(an)) for an in opdef.arg_names]
        while args and args[-1] is None and opdef.arg_names[len(args) - 1] not in named:
            args.pop()
    # attrs (Custom keeps raw strings: the prop contract passes kwargs
    # verbatim, reference operator.py register)
    keep_raw = opdef.name == "Custom"
    attrs = {}
    for k, v in kwargs.items():
        attrs[k] = parse_attr(v) if isinstance(v, str) and not keep_raw else v
    if "key" in opdef.attr_names and "key" not in attrs:
        from .. import random as _rnd

        attrs["key"] = _rnd.next_key()
    if "training" in opdef.attr_names and "training" not in attrs:
        from .. import autograd

        attrs["training"] = autograd.is_training()
    visible_rule = _VISIBLE_RULES.get(opdef.name)
    visible = visible_rule(attrs) if visible_rule else None
    result = _invoke_raw(opdef.fn, args, attrs, visible=visible, ctx=ctx)
    if opdef.mutates:
        # reference mutable-input ops (optimizer updates): extra outputs are
        # the new values of the named inputs, written back in place
        outs = result if isinstance(result, list) else [result]
        for i, mname in enumerate(opdef.mutates):
            idx = opdef.arg_names.index(mname)
            if idx < len(args) and isinstance(args[idx], NDArray):
                args[idx]._rebind(outs[1 + i]._data)
        result = outs[0]
    if out_arr is not None:
        target = result[0] if isinstance(result, list) else result
        out_arr._rebind(target._data)
        return out_arr
    return result


def _binary_dispatch(name, lhs, rhs, reverse=False):
    opdef = _registry.get(name)
    if isinstance(rhs, (np.ndarray, list, tuple)):
        rhs = array(rhs, dtype=lhs.dtype)
    a, b = (rhs, lhs) if reverse else (lhs, rhs)
    return _invoke(opdef, (a, b), {})


def _make_op_func(opdef, public_name):
    def op_func(*args, **kwargs):
        return _invoke(opdef, args, kwargs)

    op_func.__name__ = public_name.lstrip("_")
    op_func.__qualname__ = op_func.__name__
    op_func.__doc__ = opdef.__doc__
    op_func.opdef = opdef
    return op_func


# build the `op` namespace module with every registered op (incl. aliases)
op = types.ModuleType(__name__ + ".op")
op.__doc__ = "All registered operators (reference mx.nd.op namespace)."
for _name in _registry.list_ops(include_aliases=True):
    _f = _make_op_func(_registry.get(_name), _name)
    setattr(op, _name, _f)
    if not hasattr(sys.modules[__name__], _name):
        setattr(sys.modules[__name__], _name, _f)
sys.modules[op.__name__] = op

# contrib namespace: `_contrib_Foo` → `nd.contrib.Foo` (reference
# python/mxnet/ndarray/contrib.py generated the same way)
contrib = types.ModuleType(__name__ + ".contrib")
contrib.__doc__ = "Contrib (experimental) operators (reference mx.nd.contrib)."
for _name in _registry.list_ops(include_aliases=True):
    if _name.startswith("_contrib_"):
        setattr(contrib, _name[len("_contrib_"):], _make_op_func(_registry.get(_name), _name))
sys.modules[contrib.__name__] = contrib


def __getattr__(name):
    """Ops registered AFTER import (ops.registry.register at runtime —
    tutorials, tests, user extensions) resolve dynamically (PEP 562)."""
    if not name.startswith("__") and _registry.exists(name):
        return _make_op_func(_registry.get(name), name)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))


# ---------------------------------------------------------------------------
# creation functions with ctx handling (reference ndarray.py zeros/ones/...)
# ---------------------------------------------------------------------------


def zeros(shape, ctx=None, dtype="float32", stype=None, **kwargs):
    import jax.numpy as jnp

    if stype is not None and stype != "default":
        from . import sparse as _sparse

        return _sparse.zeros(stype, shape, ctx=ctx, dtype=dtype or "float32")
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    out = jnp.zeros(shape, dtype=dtype_np(dtype or "float32"))
    return _wrap(_to_device(out, ctx) if ctx else out, ctx)


def ones(shape, ctx=None, dtype="float32", **kwargs):
    import jax.numpy as jnp

    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    out = jnp.ones(shape, dtype=dtype_np(dtype or "float32"))
    return _wrap(_to_device(out, ctx) if ctx else out, ctx)


def full(shape, val, ctx=None, dtype="float32", **kwargs):
    import jax.numpy as jnp

    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    out = jnp.full(shape, val, dtype=dtype_np(dtype or "float32"))
    return _wrap(_to_device(out, ctx) if ctx else out, ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    import jax.numpy as jnp

    out = jnp.arange(start, stop, step, dtype=dtype_np(dtype or "float32"))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return _wrap(_to_device(out, ctx) if ctx else out, ctx)


def maximum(lhs, rhs):
    """Elementwise max, scalar-aware (reference python/mxnet/ndarray/ndarray.py maximum)."""
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return _invoke(_registry.get("broadcast_maximum"), (lhs, rhs), {})
    if isinstance(lhs, NDArray):
        return _invoke(_registry.get("_maximum_scalar"), (lhs,), {"scalar": float(rhs)})
    return _invoke(_registry.get("_maximum_scalar"), (rhs,), {"scalar": float(lhs)})


def minimum(lhs, rhs):
    """Elementwise min, scalar-aware (reference ndarray.py minimum)."""
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return _invoke(_registry.get("broadcast_minimum"), (lhs, rhs), {})
    if isinstance(lhs, NDArray):
        return _invoke(_registry.get("_minimum_scalar"), (lhs,), {"scalar": float(rhs)})
    return _invoke(_registry.get("_minimum_scalar"), (rhs,), {"scalar": float(lhs)})


def zeros_like(arr, **kw):
    return _invoke(_registry.get("zeros_like"), (arr,), kw)


def ones_like(arr, **kw):
    return _invoke(_registry.get("ones_like"), (arr,), kw)


# ---------------------------------------------------------------------------
# serialization (reference MXNDArraySave/Load, src/c_api/c_api.cc:131-167)
# ---------------------------------------------------------------------------


def save(fname, data):
    """Save NDArray | list | dict of NDArrays (reference nd.save).

    Format: numpy .npz with a manifest key encoding list vs dict (portable,
    replacing the reference's dmlc binary format).
    """
    def _np(v):
        return v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)

    # pass an open handle so numpy can't append ".npz" to the user's filename
    with open(fname, "wb") as f:
        if isinstance(data, (NDArray, np.ndarray)):
            np.savez(f, __mx_format__="single", a0=_np(data))
        elif isinstance(data, (list, tuple)):
            arrs = {"a%d" % i: _np(a) for i, a in enumerate(data)}
            np.savez(f, __mx_format__="list", **arrs)
        elif isinstance(data, dict):
            arrs = {"k_" + k: _np(v) for k, v in data.items()}
            np.savez(f, __mx_format__="dict", **arrs)
        else:
            raise TypeError(type(data))


def load(fname):
    """Load NDArrays saved by :func:`save`."""
    with np.load(fname, allow_pickle=False) as z:
        fmt = str(z["__mx_format__"])
        if fmt == "single":
            return [array(z["a0"])]
        if fmt == "list":
            n = len([k for k in z.files if k.startswith("a")])
            return [array(z["a%d" % i]) for i in range(n)]
        return {k[2:]: array(z[k]) for k in z.files if k.startswith("k_")}


# ---------------------------------------------------------------------------
# module-level arithmetic helpers (reference mxnet/ndarray/ndarray.py
# add/subtract/... — scalar-or-array aware; the NDArray magic methods
# already dispatch to broadcast/scalar ops, so delegate to them)
# ---------------------------------------------------------------------------

def _arith(name, op):
    def f(lhs, rhs):
        if not isinstance(lhs, NDArray) and not isinstance(rhs, NDArray):
            if np.isscalar(lhs) and np.isscalar(rhs):
                # reference _ufunc_helper returns a plain Python number
                # for scalar-scalar
                return op(lhs, rhs)
            lhs = array(lhs)
        return op(lhs, rhs)

    f.__name__ = name
    f.__doc__ = ("Element-wise %s with scalar-or-array operands "
                 "(reference ndarray.py %s)." % (name, name))
    return f


add = _arith("add", lambda l, r: l + r)
subtract = _arith("subtract", lambda l, r: l - r)
multiply = _arith("multiply", lambda l, r: l * r)
divide = _arith("divide", lambda l, r: l / r)
true_divide = _arith("true_divide", lambda l, r: l / r)
modulo = _arith("modulo", lambda l, r: l % r)
power = _arith("power", lambda l, r: l ** r)

# ---------------------------------------------------------------------------
# nd.random namespace (reference mxnet/ndarray/random.py)
# ---------------------------------------------------------------------------

random = types.ModuleType(__name__ + ".random")
random.__doc__ = "Random distribution generators (reference nd.random)."


def _make_random(fname, opname, posnames):
    opdef = _registry.get(opname)

    def rnd_func(*args, **kwargs):
        # reference nd.random samplers take their distribution params
        # positionally (mxnet/ndarray/random.py uniform(low, high, shape...));
        # map them onto the op's keyword-only params
        for name, val in zip(posnames, args):
            if name in kwargs:
                raise TypeError("%s() got multiple values for '%s'"
                                % (fname, name))
            kwargs[name] = val
        extra = args[len(posnames):]
        return _invoke(opdef, extra, kwargs)

    rnd_func.__name__ = fname
    rnd_func.__doc__ = opdef.__doc__
    return rnd_func


for _fname, _opname, _pos in [
    # trailing ctx/out: the reference samplers accept them positionally too
    # (mxnet/ndarray/random.py uniform(low, high, shape, dtype, ctx, out));
    # _invoke already handles both as keywords
    ("uniform", "_random_uniform", ("low", "high", "shape", "dtype", "ctx", "out")),
    ("normal", "_random_normal", ("loc", "scale", "shape", "dtype", "ctx", "out")),
    ("gamma", "_random_gamma", ("alpha", "beta", "shape", "dtype", "ctx", "out")),
    ("poisson", "_random_poisson", ("lam", "shape", "dtype", "ctx", "out")),
    ("negative_binomial", "_random_negative_binomial",
     ("k", "p", "shape", "dtype", "ctx", "out")),
    ("generalized_negative_binomial", "_random_generalized_negative_binomial",
     ("mu", "alpha", "shape", "dtype", "ctx", "out")),
    ("randint", "_random_randint", ("low", "high", "shape", "dtype", "ctx", "out")),
    ("multinomial", "_sample_multinomial", ()),
    ("shuffle", "_shuffle", ()),
]:
    setattr(random, _fname, _make_random(_fname, _opname, _pos))


def _random_exponential_frontend(scale=1.0, shape=(1,), dtype="float32",
                                 **kwargs):
    """Reference nd.random.exponential takes the MEAN (``scale``) and
    converts to the op's rate (mxnet/ndarray/random.py exponential:
    lam = 1/scale); the raw-rate form stays available as
    ``nd._random_exponential(lam=...)``."""
    opdef = _registry.get("_random_exponential")
    return _invoke(opdef, (), dict(lam=1.0 / scale, shape=shape,
                                   dtype=dtype, **kwargs))


random.exponential = _random_exponential_frontend
sys.modules[random.__name__] = random

# ---------------------------------------------------------------------------
# nd.sparse namespace (reference mxnet/ndarray/sparse.py)
# ---------------------------------------------------------------------------
from . import sparse  # noqa: E402
from .sparse import (  # noqa: E402,F401
    BaseSparseNDArray,
    CSRNDArray,
    RowSparseNDArray,
    cast_storage,
)

__all__ += ["sparse", "BaseSparseNDArray", "CSRNDArray", "RowSparseNDArray", "cast_storage"]

# ---------------------------------------------------------------------------
# fluent methods (reference mxnet/ndarray/ndarray.py "Convenience fluent
# method for X" set — x.log() ≡ nd.log(x) for every listed op).  Hand-written
# methods on NDArray win; only the missing ones are attached here.
# ---------------------------------------------------------------------------

_FLUENT = (
    "reshape_like zeros_like ones_like broadcast_axes repeat pad swapaxes "
    "split slice slice_axis slice_like take one_hot pick sort topk argsort "
    "argmax argmax_channel argmin clip abs sign flatten expand_dims tile "
    "transpose flip sum nansum prod nanprod mean max min norm round rint "
    "fix floor ceil trunc sin cos tan arcsin arccos arctan degrees radians "
    "sinh cosh tanh arcsinh arccosh arctanh exp expm1 log log10 log2 log1p "
    "sqrt rsqrt cbrt rcbrt square reciprocal relu sigmoid softmax "
    "log_softmax squeeze"
).split()


def _make_fluent(opname):
    opdef = _registry.get(opname)

    def fluent(self, *args, **kwargs):
        return _invoke(opdef, (self,) + args, kwargs)

    fluent.__name__ = opname
    fluent.__doc__ = ("Convenience fluent method for nd.%s (reference "
                      "ndarray.py fluent set)." % opname)
    return fluent


for _fname in _FLUENT:
    if not hasattr(NDArray, _fname) and _registry.exists(_fname):
        setattr(NDArray, _fname, _make_fluent(_fname))
