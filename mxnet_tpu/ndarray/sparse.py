"""Sparse NDArrays — reference ``python/mxnet/ndarray/sparse.py``
(CSRNDArray, RowSparseNDArray, BaseSparseNDArray) and the C++ storage types
``include/mxnet/ndarray.h:61-66`` (kDefaultStorage/kRowSparseStorage/
kCSRStorage).

TPU-first design: XLA has no native sparse tensors, so sparse here is a
*storage format* for host/optimizer/kvstore paths (embedding-style gradients,
parameter-server row pulls), not a device compute path.  RowSparse holds
``(indices, data)``; CSR holds ``(data, indices, indptr)``.  Compute that
benefits on TPU (csr dot dense) lowers to gather/segment ops under jit;
everything else densifies explicitly via ``tostype('default')``.  The
reference's fine-grained sparse kernel zoo (src/operator/tensor/ *-inl.h
sparse branches) is deliberately collapsed into these few primitives.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError, dtype_np
from .ndarray import NDArray, array as _dense_array, _wrap

__all__ = [
    "BaseSparseNDArray",
    "CSRNDArray",
    "RowSparseNDArray",
    "csr_matrix",
    "row_sparse_array",
    "cast_storage",
    "retain",
    "dot",
    "zeros",
    "empty",
    "array",
]


def _jnp():
    import jax.numpy as jnp

    return jnp


class BaseSparseNDArray(NDArray):
    """Base for sparse storage types (reference sparse.py BaseSparseNDArray).

    ``_data`` holds the *dense* materialization lazily (None until needed);
    component arrays live in subclass slots.
    """

    __slots__ = ("_shape", "_dtype", "_aux")

    def __init__(self, shape, dtype):
        super().__init__(None)
        self._shape = tuple(int(s) for s in shape)
        self._dtype = np.dtype(dtype)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    def _densify(self):
        raise NotImplementedError

    def _dense(self):
        if self._data is None:
            self._data = self._densify()
        return self._data

    def asnumpy(self):
        """Returns a dense numpy array (reference behavior)."""
        return np.asarray(self._dense())

    def todense(self):
        return _wrap(self._dense())

    def tostype(self, stype):
        if stype == self.stype:
            return self
        if stype == "default":
            return self.todense()
        return cast_storage(self.todense(), stype)

    def astype(self, dtype, copy=True):
        raise MXNetError("astype is not supported for %s; tostype('default') first" % self.stype)

    def __getitem__(self, key):
        raise MXNetError("indexing is not supported for %s storage" % self.stype)

    def __setitem__(self, key, value):
        raise MXNetError("assignment is not supported for %s storage" % self.stype)

    def _binary(self, other, op_name, reflected=False):
        """Sparse arithmetic: same-stype stays sparse; scalar mul/div keeps
        sparsity (zeros stay zero); everything else densifies."""
        import numbers
        import operator

        fn = getattr(operator, op_name)
        if reflected:
            fwd = fn
            fn = lambda a, b: fwd(b, a)  # noqa: E731
        # rs/scalar and rs*scalar keep zeros zero; scalar/rs does not
        if isinstance(other, numbers.Number) and (
            op_name == "mul" or (op_name == "truediv" and not reflected)
        ):
            out = self.copy()
            out._aux = dict(out._aux)
            out._aux["data"] = fn(out._aux["data"], other)
            out._data = None
            return out
        if isinstance(other, BaseSparseNDArray) and other.stype == self.stype:
            out = fn(self.todense(), other.todense())
            return cast_storage(out, self.stype)
        return fn(self.todense(), other)

    def __add__(self, other):
        return self._binary(other, "add")

    def __radd__(self, other):
        return self._binary(other, "add", reflected=True)

    def __sub__(self, other):
        return self._binary(other, "sub")

    def __rsub__(self, other):
        return self._binary(other, "sub", reflected=True)

    def __mul__(self, other):
        return self._binary(other, "mul")

    def __rmul__(self, other):
        return self._binary(other, "mul")

    def __truediv__(self, other):
        return self._binary(other, "truediv")

    def __rtruediv__(self, other):
        return self._binary(other, "truediv", reflected=True)

    def __repr__(self):
        return "<%s %s @%s>" % (type(self).__name__, "x".join(map(str, self._shape)), self.stype)


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse: ``data[i] == dense[indices[i]]`` (reference sparse.py:778).

    Typical producer: embedding-gradient rows.  ``indices`` is sorted unique
    int64; ``data`` has shape ``(len(indices),) + shape[1:]``.
    """

    __slots__ = ()

    def __init__(self, data, indices, shape, dtype=None):
        jnp = _jnp()
        data = jnp.asarray(data)
        dtype = dtype or data.dtype
        super().__init__(shape, dtype)
        self._aux = {
            "data": data.astype(dtype_np(dtype)) if data.dtype != np.dtype(dtype) else data,
            "indices": jnp.asarray(np.asarray(indices), dtype="int32"),
        }
        if self._aux["data"].shape[0] != self._aux["indices"].shape[0]:
            raise MXNetError(
                "row_sparse data rows (%d) != indices (%d)"
                % (self._aux["data"].shape[0], self._aux["indices"].shape[0])
            )

    @property
    def stype(self):
        return "row_sparse"

    @property
    def data(self):
        return _wrap(self._aux["data"])

    @property
    def indices(self):
        return _wrap(self._aux["indices"])

    def _densify(self):
        jnp = _jnp()
        out = jnp.zeros(self._shape, dtype=self._dtype)
        if self._aux["indices"].shape[0] == 0:
            return out
        return out.at[self._aux["indices"]].set(self._aux["data"])

    def retain(self, indices):
        return retain(self, indices)

    def copy(self):
        return RowSparseNDArray(self._aux["data"], self._aux["indices"], self._shape, self._dtype)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference sparse.py:322)."""

    __slots__ = ()

    def __init__(self, data, indices, indptr, shape, dtype=None):
        jnp = _jnp()
        data = jnp.asarray(data)
        dtype = dtype or data.dtype
        if len(shape) != 2:
            raise MXNetError("csr storage requires a 2D shape, got %s" % (shape,))
        super().__init__(shape, dtype)
        self._aux = {
            "data": data.astype(dtype_np(dtype)) if data.dtype != np.dtype(dtype) else data,
            "indices": jnp.asarray(np.asarray(indices), dtype="int32"),
            "indptr": jnp.asarray(np.asarray(indptr), dtype="int32"),
        }
        if self._aux["indptr"].shape[0] != shape[0] + 1:
            raise MXNetError("indptr length must be shape[0]+1")

    @property
    def stype(self):
        return "csr"

    @property
    def data(self):
        return _wrap(self._aux["data"])

    @property
    def indices(self):
        return _wrap(self._aux["indices"])

    @property
    def indptr(self):
        return _wrap(self._aux["indptr"])

    def _row_ids(self):
        """nnz-length row index vector expanded from indptr (host-side)."""
        indptr = np.asarray(self._aux["indptr"])
        counts = np.diff(indptr)
        return np.repeat(np.arange(self._shape[0], dtype=np.int64), counts)

    def _densify(self):
        jnp = _jnp()
        out = jnp.zeros(self._shape, dtype=self._dtype)
        if self._aux["data"].shape[0] == 0:
            return out
        rows = jnp.asarray(self._row_ids())
        return out.at[rows, self._aux["indices"]].set(self._aux["data"])

    def __getitem__(self, key):
        # row slicing mirrors reference CSRNDArray.__getitem__
        if isinstance(key, int):
            if key < 0:
                key += self._shape[0]
            if not 0 <= key < self._shape[0]:
                raise MXNetError("row index out of range")
            key = slice(key, key + 1)
        if not isinstance(key, slice) or key.step not in (None, 1):
            raise MXNetError("csr only supports contiguous row slicing")
        start, stop, _ = key.indices(self._shape[0])
        indptr = np.asarray(self._aux["indptr"])
        lo, hi = int(indptr[start]), int(indptr[stop])
        return CSRNDArray(
            self._aux["data"][lo:hi],
            self._aux["indices"][lo:hi],
            indptr[start : stop + 1] - lo,
            (stop - start, self._shape[1]),
            self._dtype,
        )

    def asscipy(self):
        import scipy.sparse as sps

        return sps.csr_matrix(
            (
                np.asarray(self._aux["data"]),
                np.asarray(self._aux["indices"]),
                np.asarray(self._aux["indptr"]),
            ),
            shape=self._shape,
        )

    def copy(self):
        return CSRNDArray(
            self._aux["data"],
            self._aux["indices"],
            self._aux["indptr"],
            self._shape,
            self._dtype,
        )


# -- creation ----------------------------------------------------------------


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Creates a RowSparseNDArray (reference sparse.py row_sparse_array).

    ``arg1`` is ``(data, indices)``, a dense array/NDArray, or another
    RowSparseNDArray.
    """
    if isinstance(arg1, RowSparseNDArray):
        return arg1.copy() if shape is None else RowSparseNDArray(
            arg1._aux["data"], arg1._aux["indices"], shape, dtype or arg1.dtype
        )
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = data._data if isinstance(data, NDArray) else np.asarray(data)
        if shape is None:
            raise MXNetError("shape is required when creating from (data, indices)")
        return RowSparseNDArray(data, np.asarray(indices), shape, dtype)
    dense = arg1 if isinstance(arg1, NDArray) else _dense_array(np.asarray(arg1), dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Creates a CSRNDArray from (data, indices, indptr), dense, or scipy."""
    if isinstance(arg1, CSRNDArray):
        return arg1.copy()
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = data._data if isinstance(data, NDArray) else np.asarray(data)
        if shape is None:
            raise MXNetError("shape is required when creating from (data, indices, indptr)")
        return CSRNDArray(data, np.asarray(indices), np.asarray(indptr), shape, dtype)
    if hasattr(arg1, "tocsr"):  # scipy sparse
        sp = arg1.tocsr()
        return CSRNDArray(sp.data, sp.indices, sp.indptr, sp.shape, dtype or sp.dtype)
    dense = arg1 if isinstance(arg1, NDArray) else _dense_array(np.asarray(arg1), dtype=dtype)
    return cast_storage(dense, "csr")


def array(source_array, ctx=None, dtype=None):
    """Sparse-aware array(): passes sparse through, densifies else."""
    if isinstance(source_array, BaseSparseNDArray):
        return source_array.copy()
    if hasattr(source_array, "tocsr"):
        return csr_matrix(source_array, dtype=dtype)
    return _dense_array(source_array, ctx=ctx, dtype=dtype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    """All-zero array of the given storage type (reference sparse.py zeros)."""
    if isinstance(shape, int):
        shape = (shape,)
    if stype == "row_sparse":
        return RowSparseNDArray(
            np.zeros((0,) + tuple(shape[1:]), dtype=dtype_np(dtype)), np.zeros(0, np.int64), shape
        )
    if stype == "csr":
        return CSRNDArray(
            np.zeros(0, dtype=dtype_np(dtype)),
            np.zeros(0, np.int64),
            np.zeros(shape[0] + 1, np.int64),
            shape,
        )
    if stype == "default":
        from . import zeros as dzeros

        return dzeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError("unknown storage type %s" % stype)


def empty(stype, shape, ctx=None, dtype="float32"):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


# -- conversion / compute ----------------------------------------------------


def cast_storage(arr, stype):
    """dense <-> sparse conversion (reference cast_storage-inl.h)."""
    if isinstance(arr, BaseSparseNDArray):
        return arr.tostype(stype)
    if not isinstance(arr, NDArray):
        arr = _dense_array(np.asarray(arr))
    if stype == "default":
        return arr
    dense = np.asarray(arr.asnumpy())
    if stype == "row_sparse":
        nz_rows = np.where(np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
        return RowSparseNDArray(dense[nz_rows], nz_rows.astype(np.int64), dense.shape, dense.dtype)
    if stype == "csr":
        if dense.ndim != 2:
            raise MXNetError("csr storage requires 2D input")
        rows, cols = np.nonzero(dense)
        indptr = np.zeros(dense.shape[0] + 1, dtype=np.int64)
        np.add.at(indptr[1:], rows, 1)
        indptr = np.cumsum(indptr)
        return CSRNDArray(dense[rows, cols], cols.astype(np.int64), indptr, dense.shape, dense.dtype)
    raise MXNetError("unknown storage type %s" % stype)


def retain(rsp, indices):
    """Keeps only the requested rows of a RowSparseNDArray (reference
    _retain; used by kvstore row_sparse pulls)."""
    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    want = np.asarray(
        indices._data if isinstance(indices, NDArray) else indices, dtype=np.int64
    )
    have = np.asarray(rsp._aux["indices"])
    # keep rows of rsp whose index is in `want`, in sorted order
    mask = np.isin(have, want)
    keep = np.where(mask)[0]
    return RowSparseNDArray(rsp._aux["data"][keep], have[keep], rsp.shape, rsp.dtype)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot.  csr × dense lowers to gather + segment-sum, the
    TPU-friendly formulation of the reference's sparse dot kernels
    (src/operator/tensor/dot-inl.h)."""
    import jax

    jnp = _jnp()
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray) and not isinstance(rhs, BaseSparseNDArray):
        if transpose_b:
            raise MXNetError("transpose_b unsupported for csr dot")
        rows = jnp.asarray(lhs._row_ids())
        cols = lhs._aux["indices"]
        vals = lhs._aux["data"]
        if transpose_a:
            # csr^T dot dense: scatter-add into output rows keyed by column
            out = jnp.zeros((lhs.shape[1], rhs.shape[1]), vals.dtype).at[cols].add(
                rhs._data[rows] * vals[:, None]
            )
            return _wrap(out)
        gathered = rhs._data[cols] * vals[:, None]  # (nnz, N)
        out = jax.ops.segment_sum(gathered, rows, num_segments=lhs.shape[0])
        return _wrap(out)
    if isinstance(lhs, BaseSparseNDArray):
        lhs = lhs.todense()
    if isinstance(rhs, BaseSparseNDArray):
        rhs = rhs.todense()
    from . import op

    return op.dot(lhs, rhs, transpose_a=transpose_a, transpose_b=transpose_b)
