"""NDArray — the imperative array type.

TPU-native re-design of reference ``include/mxnet/ndarray.h`` +
``python/mxnet/ndarray/ndarray.py:169``.  An NDArray wraps a ``jax.Array``;
JAX's async dispatch provides the engine semantics the reference built with
ThreadedEngine vars (SURVEY §7.1: wait_to_read ≡ block_until_ready).  In-place
mutation (``a += b``, ``a[1:3] = x``) rebinds the wrapped buffer — a
functional update under the hood, same observable semantics.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError, dtype_np, dtype_name
from ..context import Context, current_context

__all__ = ["NDArray", "array", "empty", "concatenate", "waitall"]


class NDArray:
    __slots__ = ("_data", "_ctx", "grad", "_grad_req", "_ag_node", "__weakref__")

    # numpy operator dispatch defers to NDArray's reflected ops
    __array_priority__ = 100.0

    def __init__(self, data, ctx=None):
        import jax

        self._data = data
        self._ctx = ctx
        self.grad = None
        self._grad_req = "null"
        self._ag_node = None

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        dt = self._data.dtype
        return np.dtype(dt) if dt.name != "bfloat16" else dt

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        if self._ctx is not None:
            return self._ctx
        try:
            dev = list(self._data.devices())[0]
        except Exception:
            return current_context()
        if dev.platform == "cpu":
            return Context("cpu", dev.id)
        return Context("tpu", dev.id)

    ctx = context

    @property
    def stype(self):
        return "default"

    # -- sync / conversion --------------------------------------------------
    def asnumpy(self):
        """Block and copy to host (reference WaitToRead + CopyFromTo)."""
        return np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def wait_to_read(self):
        self._data.block_until_ready()

    def astype(self, dtype, copy=True):
        dt = dtype_np(dtype)
        return self._taped(lambda a: a.astype(dt))

    def copy(self):
        return _wrap(self._data + 0 if self.dtype != np.dtype(bool) else self._data, self._ctx)

    def copyto(self, other):
        """Copy to another NDArray or Context (reference ndarray.py copyto)."""
        if isinstance(other, NDArray):
            other._rebind(_to_device(self._data, other.context))
            return other
        if isinstance(other, Context):
            return _wrap(_to_device(self._data, other), other)
        raise TypeError(type(other))

    def as_in_context(self, context):
        if context == self.context:
            return self
        return _wrap(_to_device(self._data, context), context)

    def as_in_ctx(self, context):
        return self.as_in_context(context)

    def to_dlpack_for_read(self):
        return self._data.__dlpack__()

    # -- autograd -----------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate gradient buffer & mark for autograd (reference autograd.mark_variables)."""
        import jax.numpy as jnp

        from .. import autograd

        self.grad = _wrap(jnp.zeros_like(self._data), self._ctx)
        self._grad_req = grad_req
        autograd._mark_variable(self)

    def detach(self):
        out = _wrap(self._data, self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward(
            [self], [out_grad] if out_grad is not None else None, retain_graph, train_mode
        )

    # -- mutation (functional rebind) ---------------------------------------
    def _rebind(self, new_data):
        if tuple(new_data.shape) != self.shape:
            raise ValueError(
                "inplace update shape mismatch: %s vs %s" % (new_data.shape, self.shape)
            )
        self._data = new_data.astype(self._data.dtype) if new_data.dtype != self._data.dtype else new_data

    def __setitem__(self, key, value):
        import jax.numpy as jnp

        if isinstance(value, NDArray):
            value = value._data
        key = _index(key)
        if key == slice(None) and not isinstance(value, (int, float)):
            value = jnp.asarray(value, dtype=self._data.dtype)
            self._rebind(jnp.broadcast_to(value, self.shape))
            return
        self._data = self._data.at[key].set(
            value if isinstance(value, (int, float)) else jnp.asarray(value, dtype=self._data.dtype)
        )

    def _taped(self, fn):
        """Run a pure unary fn through the frontend so autograd tapes it."""
        from . import _invoke_raw

        return _invoke_raw(fn, [self], {})

    def __getitem__(self, key):
        key = _index(key)
        return self._taped(lambda a: a[key])

    # -- shape ops ----------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        from ..ops.matrix import infer_reshape

        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if not shape:
            shape = kwargs.get("shape")
        reverse = kwargs.get("reverse", False)
        tgt = infer_reshape(self.shape, shape, reverse)
        return self._taped(lambda a: a.reshape(tgt))

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def expand_dims(self, axis):
        import jax.numpy as jnp

        return self._taped(lambda a: jnp.expand_dims(a, axis))

    def squeeze(self, axis=None):
        import jax.numpy as jnp

        return self._taped(lambda a: jnp.squeeze(a, axis))

    def flatten(self):
        return self.reshape((self.shape[0], -1))

    def transpose(self, axes=None):
        import jax.numpy as jnp

        if axes is None:
            axes = tuple(reversed(range(self.ndim)))
        return self._taped(lambda a: jnp.transpose(a, axes))

    @property
    def T(self):
        return self.transpose()

    def swapaxes(self, dim1, dim2):
        import jax.numpy as jnp

        return self._taped(lambda a: jnp.swapaxes(a, dim1, dim2))

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        from . import op

        return op.split(self, num_outputs=num_outputs, axis=axis, squeeze_axis=squeeze_axis)

    # -- reductions (ndarray methods mirror op names) -----------------------
    def _reduce(self, name, axis=None, keepdims=False):
        from . import op

        return getattr(op, name)(self, axis=axis, keepdims=keepdims)

    def sum(self, axis=None, keepdims=False):
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._reduce("mean", axis, keepdims)

    def max(self, axis=None, keepdims=False):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce("min", axis, keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._reduce("prod", axis, keepdims)

    def norm(self, **kwargs):
        from . import op

        return op.norm(self, **kwargs)

    def argmax(self, axis=None):
        from . import op

        return op.argmax(self, axis=axis)

    def argmin(self, axis=None):
        from . import op

        return op.argmin(self, axis=axis)

    def clip(self, a_min, a_max):
        from . import op

        return op.clip(self, a_min=a_min, a_max=a_max)

    def abs(self):
        from . import op

        return op.abs(self)

    def sqrt(self):
        from . import op

        return op.sqrt(self)

    def square(self):
        from . import op

        return op.square(self)

    def sign(self):
        from . import op

        return op.sign(self)

    def log_softmax(self, axis=-1):
        from . import op

        return op.log_softmax(self, axis=axis)

    def softmax(self, axis=-1):
        from . import op

        return op.softmax(self, axis=axis)

    def one_hot(self, depth, **kw):
        from . import op

        return op.one_hot(self, depth=depth, **kw)

    def take(self, indices, axis=0, mode="clip"):
        from . import op

        return op.take(self, indices, axis=axis, mode=mode)

    def topk(self, **kw):
        from . import op

        return op.topk(self, **kw)

    def tile(self, reps):
        from . import op

        return op.tile(self, reps=reps)

    def pad(self, **kw):
        from . import op

        return op.pad(self, **kw)

    def slice_axis(self, axis, begin, end):
        from . import op

        return op.slice_axis(self, axis=axis, begin=begin, end=end)

    def broadcast_to(self, shape):
        from . import op

        return op.broadcast_to(self, shape=shape)

    def broadcast_like(self, other):
        from . import op

        return op.broadcast_like(self, other)

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse

        return sparse.cast_storage(self, stype=stype)

    # -- python protocol ----------------------------------------------------
    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple elements is ambiguous.")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        arr = self.asnumpy()
        return "\n%s\n<NDArray %s @%s>" % (arr, "x".join(map(str, self.shape)), self.context)

    def __hash__(self):
        return id(self)

    # arithmetic — routed through the op registry so autograd tapes them
    def _binop(self, name, other, reverse=False):
        from . import _binary_dispatch

        return _binary_dispatch(name, self, other, reverse)

    def __add__(self, o):
        return self._binop("broadcast_add", o)

    def __radd__(self, o):
        return self._binop("broadcast_add", o, True)

    def __sub__(self, o):
        return self._binop("broadcast_sub", o)

    def __rsub__(self, o):
        return self._binop("broadcast_sub", o, True)

    def __mul__(self, o):
        return self._binop("broadcast_mul", o)

    def __rmul__(self, o):
        return self._binop("broadcast_mul", o, True)

    def __truediv__(self, o):
        return self._binop("broadcast_div", o)

    def __rtruediv__(self, o):
        return self._binop("broadcast_div", o, True)

    def __div__(self, o):
        return self.__truediv__(o)

    def __mod__(self, o):
        return self._binop("broadcast_mod", o)

    def __rmod__(self, o):
        return self._binop("broadcast_mod", o, True)

    def __pow__(self, o):
        return self._binop("broadcast_power", o)

    def __rpow__(self, o):
        return self._binop("broadcast_power", o, True)

    def __neg__(self):
        from . import op

        return op.negative(self)

    def __eq__(self, o):
        return self._binop("broadcast_equal", o)

    def __ne__(self, o):
        return self._binop("broadcast_not_equal", o)

    def __gt__(self, o):
        return self._binop("broadcast_greater", o)

    def __ge__(self, o):
        return self._binop("broadcast_greater_equal", o)

    def __lt__(self, o):
        return self._binop("broadcast_lesser", o)

    def __le__(self, o):
        return self._binop("broadcast_lesser_equal", o)

    def __iadd__(self, o):
        self._rebind(self.__add__(o)._data)
        return self

    def __isub__(self, o):
        self._rebind(self.__sub__(o)._data)
        return self

    def __imul__(self, o):
        self._rebind(self.__mul__(o)._data)
        return self

    def __itruediv__(self, o):
        self._rebind(self.__truediv__(o)._data)
        return self


def _index(key):
    """Normalize an index: NDArray indices → jax arrays."""
    if isinstance(key, NDArray):
        return key._data.astype("int32")
    if isinstance(key, tuple):
        return tuple(_index(k) for k in key)
    return key


def _wrap(jarr, ctx=None):
    return NDArray(jarr, ctx)


def _to_device(jarr, ctx):
    import jax

    return jax.device_put(jarr, ctx.jax_device)


# ---------------------------------------------------------------------------
# creation helpers (reference ndarray.py array/empty/...)
# ---------------------------------------------------------------------------


def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like (reference ndarray.py:array)."""
    import jax
    import jax.numpy as jnp

    if isinstance(source_array, NDArray):
        src = source_array._data
        if dtype is not None:
            src = src.astype(dtype_np(dtype))
        if ctx is not None:
            src = _to_device(src, ctx)
        return _wrap(src, ctx)
    np_arr = np.asarray(source_array)
    if dtype is None:
        if np_arr.dtype == np.float64:
            dtype = np.float32  # MXNet default_dtype convention
        elif np_arr.dtype == np.int64:
            dtype = np.int32  # TPU-native: x64 disabled under jit
        else:
            dtype = np_arr.dtype
    jarr = jnp.asarray(np_arr, dtype=dtype_np(dtype) if isinstance(dtype, str) else dtype)
    if ctx is not None:
        jarr = _to_device(jarr, ctx)
    return _wrap(jarr, ctx)


def empty(shape, ctx=None, dtype="float32"):
    import jax.numpy as jnp

    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    jarr = jnp.zeros(shape, dtype=dtype_np(dtype or "float32"))
    if ctx is not None:
        jarr = _to_device(jarr, ctx)
    return _wrap(jarr, ctx)


def concatenate(arrays, axis=0, always_copy=True):
    import jax.numpy as jnp

    return _wrap(jnp.concatenate([a._data for a in arrays], axis=axis))


def waitall():
    """Block until all async computation completes (reference MXNDArrayWaitAll)."""
    import jax

    (jax.device_put(0.0) + 0).block_until_ready()
