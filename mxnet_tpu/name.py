"""Automatic naming support (reference ``python/mxnet/name.py``).

The implementations live in :mod:`mxnet_tpu.base` because Symbol building
needs them at import time; this module keeps the reference's import path
(``mx.name.NameManager`` / ``mx.name.Prefix``).
"""
from .base import NameManager, Prefix

__all__ = ["NameManager", "Prefix"]
