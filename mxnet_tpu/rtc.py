"""Runtime kernel compilation facade — reference ``python/mxnet/rtc.py``
(CudaModule :58, CudaKernel :167 over ``src/common/rtc.cc`` NVRTC).

There is no CUDA on TPU; the TPU-native equivalent of runtime-compiled
kernels is a **Pallas** kernel (jax.experimental.pallas), which jits through
XLA:TPU. This module keeps the reference API importable and fails loudly
with that guidance at use."""
from __future__ import annotations

from .base import MXNetError

__all__ = ["CudaModule", "CudaKernel"]

_MSG = (
    "mx.rtc compiles CUDA C at runtime, which does not exist on TPU. "
    "Write the kernel with jax.experimental.pallas instead (see "
    "/opt/skills/guides/pallas_guide.md for the TPU kernel playbook) and "
    "register it as an operator with mxnet_tpu.ops.registry.register."
)


class CudaModule:
    """(reference rtc.py:58) Unavailable on TPU — raises with guidance."""

    def __init__(self, source, options=(), exports=()):
        raise MXNetError(_MSG)


class CudaKernel:
    """(reference rtc.py:167) Unavailable on TPU — raises with guidance."""

    def __init__(self, *args, **kwargs):
        raise MXNetError(_MSG)
