"""Detection image pipeline — reference ``python/mxnet/image/detection.py``
(DetAugmenter :39, DetHorizontalFlipAug :126, DetRandomCropAug :152,
DetRandomPadAug :324, CreateDetAugmenter :483, ImageDetIter :625).

Label convention (same as the reference's packed det records): the flat label
is ``[header_width, object_width, <extra header...>, obj0, obj1, ...]`` where
each object is ``object_width`` floats ``[class, xmin, ymin, xmax, ymax, ...]``
with coordinates normalized to [0, 1].  Batch labels are padded with -1 rows.
"""
from __future__ import annotations

import json
import random as pyrandom

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import array
from .. import io
from . import image as img_mod

__all__ = [
    "DetAugmenter",
    "DetBorrowAug",
    "DetRandomSelectAug",
    "DetHorizontalFlipAug",
    "DetRandomCropAug",
    "DetRandomPadAug",
    "CreateMultiRandCropAugmenter",
    "CreateDetAugmenter",
    "ImageDetIter",
]


class DetAugmenter:
    """Detection augmenter base: __call__(src, label) (reference :39)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in self._kwargs.items():
            if isinstance(v, np.ndarray):
                self._kwargs[k] = v.tolist()

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lifts an image-only Augmenter into a det augmenter (reference :65)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, img_mod.Augmenter):
            raise RuntimeError("Validation: invalid augmenter to borrow from")
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def dumps(self):
        return [self.__class__.__name__.lower(), self.augmenter.dumps()]

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly applies one of aug_list, or skips (reference :90)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        if not isinstance(aug_list, (list, tuple)):
            aug_list = [aug_list]
        for aug in aug_list:
            if not isinstance(aug, DetAugmenter):
                raise RuntimeError("Validation: invalid augmenter in aug_list")
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def dumps(self):
        return [self.__class__.__name__.lower(), [x.dumps() for x in self.aug_list]]

    def __call__(self, src, label):
        if pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flips image and x-coordinates with probability p (reference :126)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = np.asarray(src)[:, ::-1]
            label = label.copy()
            tmp = 1.0 - label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = tmp
        return src, label


def _box_iob(crop, boxes):
    """Intersection-over-box-area of crop (x1,y1,x2,y2) vs boxes (N,4)."""
    ix1 = np.maximum(crop[0], boxes[:, 0])
    iy1 = np.maximum(crop[1], boxes[:, 1])
    ix2 = np.minimum(crop[2], boxes[:, 2])
    iy2 = np.minimum(crop[3], boxes[:, 3])
    iw = np.maximum(0.0, ix2 - ix1)
    ih = np.maximum(0.0, iy2 - iy1)
    area = np.maximum(1e-12, (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1]))
    return iw * ih / area


class DetRandomCropAug(DetAugmenter):
    """Random crop with object-coverage constraints (reference :152).

    Samples crops until one covers at least ``min_object_covered`` of some
    object; objects whose centers fall outside the crop are dropped, the rest
    are clipped and renormalized.
    """

    def __init__(
        self,
        min_object_covered=0.1,
        aspect_ratio_range=(0.75, 1.33),
        area_range=(0.05, 1.0),
        min_eject_coverage=0.3,
        max_attempts=50,
    ):
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, 1.0)
        super().__init__(
            min_object_covered=min_object_covered,
            aspect_ratio_range=aspect_ratio_range,
            area_range=area_range,
            min_eject_coverage=min_eject_coverage,
            max_attempts=max_attempts,
        )
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts
        self.enabled = area_range[1] > area_range[0] or area_range[1] < 1.0

    def _update_labels(self, label, crop):
        """Returns updated labels for crop (x1,y1,x2,y2 normalized) or None."""
        x1, y1, x2, y2 = crop
        cw, ch = max(1e-12, x2 - x1), max(1e-12, y2 - y1)
        boxes = label[:, 1:5]
        coverage = _box_iob(np.asarray(crop), boxes)
        centers_x = (boxes[:, 0] + boxes[:, 2]) / 2
        centers_y = (boxes[:, 1] + boxes[:, 3]) / 2
        keep = (
            (centers_x > x1)
            & (centers_x < x2)
            & (centers_y > y1)
            & (centers_y < y2)
            & (coverage >= self.min_eject_coverage)
        )
        if not keep.any():
            return None
        out = label[keep].copy()
        out[:, 1] = np.clip((out[:, 1] - x1) / cw, 0, 1)
        out[:, 2] = np.clip((out[:, 2] - y1) / ch, 0, 1)
        out[:, 3] = np.clip((out[:, 3] - x1) / cw, 0, 1)
        out[:, 4] = np.clip((out[:, 4] - y1) / ch, 0, 1)
        return out

    def _sample_crop(self, label):
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            w = min(1.0, np.sqrt(area * ratio))
            h = min(1.0, np.sqrt(area / ratio))
            x1 = pyrandom.uniform(0.0, 1.0 - w)
            y1 = pyrandom.uniform(0.0, 1.0 - h)
            crop = (x1, y1, x1 + w, y1 + h)
            coverage = _box_iob(np.asarray(crop), label[:, 1:5])
            if coverage.max() >= self.min_object_covered:
                new_label = self._update_labels(label, crop)
                if new_label is not None:
                    return crop, new_label
        return None, None

    def __call__(self, src, label):
        if not self.enabled or label.shape[0] == 0:
            return src, label
        crop, new_label = self._sample_crop(label)
        if crop is None:
            return src, label
        src = np.asarray(src)
        h, w = src.shape[:2]
        x1 = int(crop[0] * w)
        y1 = int(crop[1] * h)
        x2 = max(x1 + 1, int(crop[2] * w))
        y2 = max(y1 + 1, int(crop[3] * h))
        return src[y1:y2, x1:x2], new_label


class DetRandomPadAug(DetAugmenter):
    """Random expansion padding with fill value (reference :324)."""

    def __init__(
        self,
        aspect_ratio_range=(0.75, 1.33),
        area_range=(1.0, 3.0),
        max_attempts=50,
        pad_val=(127, 127, 127),
    ):
        if not isinstance(pad_val, (tuple, list)):
            pad_val = (pad_val,)
        super().__init__(
            aspect_ratio_range=aspect_ratio_range,
            area_range=area_range,
            max_attempts=max_attempts,
            pad_val=pad_val,
        )
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val
        self.enabled = area_range[1] > 1.0

    def __call__(self, src, label):
        if not self.enabled:
            return src, label
        src = np.asarray(src)
        h, w = src.shape[:2]
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range) * (w / h)
            nw = int(w * np.sqrt(area * ratio))
            nh = int(h * np.sqrt(area / ratio))
            if nw < w or nh < h:
                continue
            x0 = pyrandom.randint(0, nw - w)
            y0 = pyrandom.randint(0, nh - h)
            c = src.shape[2] if src.ndim == 3 else 1
            canvas = np.empty((nh, nw, c), dtype=src.dtype)
            canvas[:] = np.asarray(self.pad_val[:c], dtype=src.dtype)
            canvas[y0 : y0 + h, x0 : x0 + w] = src.reshape(h, w, c)
            label = label.copy()
            label[:, 1] = (label[:, 1] * w + x0) / nw
            label[:, 2] = (label[:, 2] * h + y0) / nh
            label[:, 3] = (label[:, 3] * w + x0) / nw
            label[:, 4] = (label[:, 4] * h + y0) / nh
            return canvas, label
        return src, label


def CreateMultiRandCropAugmenter(
    min_object_covered=0.1,
    aspect_ratio_range=(0.75, 1.33),
    area_range=(0.05, 1.0),
    min_eject_coverage=0.3,
    max_attempts=50,
    skip_prob=0,
):
    """One DetRandomSelectAug over per-threshold crop augmenters (reference :418)."""

    def _as_list(x):
        return list(x) if isinstance(x, (list, tuple)) and isinstance(x[0], (list, tuple)) else [x]

    covered = min_object_covered if isinstance(min_object_covered, (list, tuple)) else [min_object_covered]
    ratios = _as_list(aspect_ratio_range)
    areas = _as_list(area_range)
    ejects = min_eject_coverage if isinstance(min_eject_coverage, (list, tuple)) else [min_eject_coverage]
    n = max(len(covered), len(ratios), len(areas), len(ejects))

    def _pick(lst, i):
        return lst[i] if i < len(lst) else lst[-1]

    augs = [
        DetRandomCropAug(
            min_object_covered=_pick(covered, i),
            aspect_ratio_range=_pick(ratios, i),
            area_range=_pick(areas, i),
            min_eject_coverage=_pick(ejects, i),
            max_attempts=max_attempts,
        )
        for i in range(n)
    ]
    return DetRandomSelectAug(augs, skip_prob=skip_prob)


def CreateDetAugmenter(
    data_shape,
    resize=0,
    rand_crop=0,
    rand_pad=0,
    rand_gray=0,
    rand_mirror=False,
    mean=None,
    std=None,
    brightness=0,
    contrast=0,
    saturation=0,
    pca_noise=0,
    hue=0,
    inter_method=2,
    min_object_covered=0.1,
    aspect_ratio_range=(0.75, 1.33),
    area_range=(0.05, 3.0),
    min_eject_coverage=0.3,
    max_attempts=50,
    pad_val=(127, 127, 127),
):
    """Standard detection augmentation list (reference :483)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(img_mod.ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop_augs = CreateMultiRandCropAugmenter(
            min_object_covered=min_object_covered,
            aspect_ratio_range=aspect_ratio_range,
            area_range=(area_range[0], min(1.0, area_range[1])),
            min_eject_coverage=min_eject_coverage,
            max_attempts=max_attempts,
            skip_prob=1 - rand_crop,
        )
        auglist.append(crop_augs)
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        auglist.append(
            DetRandomSelectAug(
                [
                    DetRandomPadAug(
                        aspect_ratio_range, (1.0, max(1.0, area_range[1])), max_attempts, pad_val
                    )
                ],
                1 - rand_pad,
            )
        )
    auglist.append(DetBorrowAug(img_mod.ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(img_mod.CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(img_mod.ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(img_mod.HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array(
            [
                [-0.5675, 0.7192, 0.4009],
                [-0.5808, -0.0045, -0.8140],
                [-0.5836, -0.6948, 0.4203],
            ]
        )
        auglist.append(DetBorrowAug(img_mod.LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(img_mod.RandomGrayAug(rand_gray)))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(img_mod.ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(img_mod.ImageIter):
    """Detection iterator yielding (data, padded object labels) (reference :625)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None, path_imglist=None,
                 path_root=None, shuffle=False, aug_list=None, imglist=None,
                 object_width=5, max_objects=None, data_name="data",
                 label_name="label", last_batch_handle="pad", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **kwargs)
            kwargs = {}
        super().__init__(
            batch_size=batch_size,
            data_shape=data_shape,
            path_imgrec=path_imgrec,
            path_imglist=path_imglist,
            path_root=path_root,
            shuffle=shuffle,
            aug_list=[],
            imglist=imglist,
            data_name=data_name,
            label_name=label_name,
            last_batch_handle=last_batch_handle,
        )
        self.det_auglist = aug_list
        self.object_width = object_width
        if max_objects is None:
            max_objects = self._scan_max_objects()
        self.max_objects = max_objects

    def _parse_label(self, label):
        """Flat packed label -> (N, object_width) array (reference _parse_label)."""
        raw = np.asarray(label, dtype=np.float32).ravel()
        if raw.size < 2:
            raise MXNetError("label must start with [header_width, object_width]")
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if obj_width < 5:
            raise MXNetError("object width must be >= 5 (class + 4 coords)")
        body = raw[header_width:]
        n = body.size // obj_width
        return body[: n * obj_width].reshape(n, obj_width)

    def _iter_labels(self):
        """Yields raw labels without decoding any image bytes."""
        from .. import recordio as rio

        if self.imgrec is not None:
            for k in self.imgrec.keys:
                yield rio.unpack(self.imgrec.read_idx(k))[0].label
        elif hasattr(self, "_records"):
            for r in self._records:
                yield rio.unpack(r)[0].label
        else:
            for idx in self.imglist:
                yield self.imglist[idx][0]

    def _scan_max_objects(self):
        mx_obj = 1
        for label in self._iter_labels():
            mx_obj = max(mx_obj, self._parse_label(label).shape[0])
        return mx_obj

    @property
    def provide_label(self):
        return [
            io.DataDesc(
                self.label_name,
                (self.batch_size, self.max_objects, self.object_width),
                np.float32,
            )
        ]

    def next(self):
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), dtype=np.float32)
        labels = np.full(
            (self.batch_size, self.max_objects, self.object_width), -1.0, dtype=np.float32
        )
        i = 0
        try:
            while i < self.batch_size:
                raw_label, img = self.next_sample()
                obj = self._parse_label(raw_label)
                for aug in self.det_auglist:
                    img, obj = aug(img, obj)
                if img.ndim == 2:
                    img = img[..., None]
                if img.shape[:2] != (h, w):
                    raise MXNetError(
                        "augmented image shape %s != data_shape %s" % (img.shape, self.data_shape)
                    )
                data[i] = np.asarray(img, dtype=np.float32).transpose(2, 0, 1)[:c]
                n = min(obj.shape[0], self.max_objects)
                labels[i, :n, : obj.shape[1]] = obj[:n, : self.object_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
            if self.last_batch_handle == "discard":
                raise
        return io.DataBatch(
            data=[array(data)],
            label=[array(labels)],
            pad=self.batch_size - i,
            provide_data=self.provide_data,
            provide_label=self.provide_label,
        )
