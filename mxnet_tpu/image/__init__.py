"""Image processing + iterators — reference ``python/mxnet/image/``."""
from .image import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from . import image
from . import detection

__all__ = image.__all__ + detection.__all__
