"""Image utilities and ImageIter — reference ``python/mxnet/image/image.py``
(imread :45, imdecode :86, resize_short :230, crops :292-509, Augmenter
classes :493-901, CreateAugmenter :903, ImageIter :1017).

TPU-first design note: the reference runs augmenters on NDArrays through the
dependency engine; here the whole augmentation pipeline is host-side numpy
(uint8/float32 HWC) and only the final batch is materialized as an NDArray —
host work stays off the device, the device sees one NCHW batch per step.
Functions accept NDArray or numpy and return numpy.
"""
from __future__ import annotations

import os
import random as pyrandom

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array
from .. import io
from .. import recordio

__all__ = [
    "imread",
    "imdecode",
    "scale_down",
    "resize_short",
    "imresize",
    "fixed_crop",
    "random_crop",
    "center_crop",
    "color_normalize",
    "random_size_crop",
    "Augmenter",
    "SequentialAug",
    "ResizeAug",
    "ForceResizeAug",
    "RandomCropAug",
    "RandomSizedCropAug",
    "CenterCropAug",
    "RandomOrderAug",
    "BrightnessJitterAug",
    "ContrastJitterAug",
    "SaturationJitterAug",
    "HueJitterAug",
    "ColorJitterAug",
    "LightingAug",
    "ColorNormalizeAug",
    "RandomGrayAug",
    "HorizontalFlipAug",
    "CastAug",
    "CreateAugmenter",
    "ImageIter",
]

_GRAY_COEF = np.array([0.299, 0.587, 0.114], dtype=np.float32)


def _to_np(img):
    if isinstance(img, NDArray):
        return img.asnumpy()
    return np.asarray(img)


def imdecode(buf, flag=1, to_rgb=True, **kwargs):
    """Decodes an image byte buffer to an HWC array (reference :86; same
    positional order: ``imdecode(buf, flag, to_rgb)``).

    Uses the native JPEG decoder (src/io/image_decode.cc) when available,
    PIL otherwise.  ``flag=0`` decodes to grayscale (H, W, 1).
    """
    if isinstance(buf, np.ndarray) and buf.dtype == np.uint8:
        buf = buf.tobytes()
    img = recordio._decode_image(bytes(buf))
    if not to_rgb:
        img = img[..., ::-1]  # BGR like OpenCV default
    if flag == 0:
        img = (img.astype(np.float32) @ _GRAY_COEF).astype(np.uint8)[..., None]
    return img


def imread(filename, *args, **kwargs):
    """Reads and decodes an image file (reference :45)."""
    if not os.path.isfile(filename):
        raise MXNetError("image file %s not found" % filename)
    with open(filename, "rb") as f:
        return imdecode(f.read(), *args, **kwargs)


def scale_down(src_size, size):
    """Scales requested crop size down to fit the source (reference :140)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


_PIL_INTERP = {0: 0, 1: 2, 2: 3, 3: 0, 4: 1}  # cv2 code -> PIL filter


def _get_interp_method(interp, sizes=()):
    """Maps cv2-style interp codes incl. 9/10 auto modes (reference :175)."""
    if interp == 9:
        if sizes:
            oh, ow, _, nh, nw = sizes[0], sizes[1], None, sizes[2], sizes[3]
            return 2 if nh > oh and nw > ow else 3
        return 2
    if interp == 10:
        return pyrandom.randint(0, 4)
    if interp not in (0, 1, 2, 3, 4):
        raise ValueError("Unknown interp method %d" % interp)
    return interp


def imresize(src, w, h, interp=2):
    """Resizes HWC image to (h, w) (reference mx.image.imresize)."""
    from PIL import Image

    src = _to_np(src)
    squeeze = False
    if src.ndim == 3 and src.shape[2] == 1:
        src = src[..., 0]
        squeeze = True
    dtype = src.dtype
    pil = Image.fromarray(src.astype(np.uint8) if dtype != np.uint8 else src)
    interp = _get_interp_method(interp, (src.shape[0], src.shape[1], h, w))
    out = np.asarray(pil.resize((w, h), resample=_PIL_INTERP[interp]))
    if squeeze:
        out = out[..., None]
    return out.astype(dtype) if dtype != np.uint8 else out


def resize_short(src, size, interp=2):
    """Resizes so the shorter edge equals size (reference :230)."""
    src = _to_np(src)
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crops a fixed region, optionally resizing to size (reference :292)."""
    src = _to_np(src)
    out = src[y0 : y0 + h, x0 : x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp=interp)
    return out


def random_crop(src, size, interp=2):
    """Randomly crops to size, scaling down if needed (reference :324).

    Returns (cropped, (x0, y0, w, h)).
    """
    src = _to_np(src)
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """Center-crops to size (reference :363).  Returns (cropped, region)."""
    src = _to_np(src)
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    """(src - mean) / std in float32 (reference :412)."""
    src = _to_np(src).astype(np.float32)
    src = src - np.asarray(mean, dtype=np.float32)
    if std is not None:
        src = src / np.asarray(std, dtype=np.float32)
    return src


def random_size_crop(src, size, area, ratio, interp=2, **kwargs):
    """Random crop with area/aspect-ratio constraints (reference :436)."""
    src = _to_np(src)
    h, w = src.shape[:2]
    src_area = h * w
    if "min_area" in kwargs:
        area = kwargs.pop("min_area")
    assert not kwargs, "unexpected keyword arguments: %s" % str(kwargs.keys())
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


class Augmenter:
    """Image augmenter base (reference :493)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in self._kwargs.items():
            if isinstance(v, np.ndarray):
                self._kwargs[k] = v.tolist()

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    """Applies a list of augmenters in order (reference :519)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def dumps(self):
        return [self.__class__.__name__.lower(), [t.dumps() for t in self.ts]]

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    """Resize shorter edge (reference :542)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    """Force resize to (w, h) (reference :562)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        src = _to_np(src)
        sizes = (src.shape[0], src.shape[1], self.size[1], self.size[0])
        return imresize(src, *self.size, interp=_get_interp_method(self.interp, sizes))


class RandomCropAug(Augmenter):
    """Random crop to size (reference :583)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    """Random area/ratio crop (reference :603)."""

    def __init__(self, size, area, ratio, interp=2, **kwargs):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        if "min_area" in kwargs:
            area = kwargs.pop("min_area")
        assert not kwargs, "unexpected keyword arguments: %s" % str(kwargs.keys())
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio, self.interp)[0]


class CenterCropAug(Augmenter):
    """Center crop (reference :637)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomOrderAug(Augmenter):
    """Applies augmenters in random order (reference :657)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def dumps(self):
        return [self.__class__.__name__.lower(), [t.dumps() for t in self.ts]]

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class BrightnessJitterAug(Augmenter):
    """src *= 1 + U(-b, b) (reference :681)."""

    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return _to_np(src).astype(np.float32) * alpha


class ContrastJitterAug(Augmenter):
    """Blend with mean gray level (reference :700)."""

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        src = _to_np(src).astype(np.float32)
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        gray = src @ _GRAY_COEF
        gray_mean = (1.0 - alpha) * gray.mean()
        return src * alpha + gray_mean


class SaturationJitterAug(Augmenter):
    """Blend with per-pixel gray (reference :723)."""

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        src = _to_np(src).astype(np.float32)
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        gray = (src @ _GRAY_COEF)[..., None] * (1.0 - alpha)
        return src * alpha + gray


class HueJitterAug(Augmenter):
    """Rotates hue in YIQ space (reference :747)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array(
            [[0.299, 0.587, 0.114], [0.596, -0.274, -0.321], [0.211, -0.523, 0.311]],
            dtype=np.float32,
        )
        self.ityiq = np.array(
            [[1.0, 0.956, 0.621], [1.0, -0.272, -0.647], [1.0, -1.107, 1.705]],
            dtype=np.float32,
        )

    def __call__(self, src):
        src = _to_np(src).astype(np.float32)
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]], dtype=np.float32)
        t = self.ityiq @ bt @ self.tyiq
        return src @ t.T


class ColorJitterAug(RandomOrderAug):
    """Random-order brightness/contrast/saturation (reference :781)."""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA-based lighting noise (reference :804)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd, eigval=eigval, eigvec=eigvec)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, dtype=np.float32)
        self.eigvec = np.asarray(eigvec, dtype=np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,)).astype(np.float32)
        rgb = self.eigvec @ (alpha * self.eigval)
        return _to_np(src).astype(np.float32) + rgb


class ColorNormalizeAug(Augmenter):
    """Mean/std normalization (reference :830)."""

    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = None if mean is None else np.asarray(mean, dtype=np.float32)
        self.std = None if std is None else np.asarray(std, dtype=np.float32)

    def __call__(self, src):
        src = _to_np(src).astype(np.float32)
        if self.mean is not None:
            src = src - self.mean
        if self.std is not None:
            src = src / self.std
        return src


class RandomGrayAug(Augmenter):
    """Randomly converts to gray (reference :850)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        src = _to_np(src)
        if pyrandom.random() < self.p:
            gray = src.astype(np.float32) @ _GRAY_COEF
            src = np.repeat(gray[..., None], 3, axis=-1)
        return src


class HorizontalFlipAug(Augmenter):
    """Random horizontal flip (reference :872)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return _to_np(src)[:, ::-1]
        return _to_np(src)


class CastAug(Augmenter):
    """Cast to dtype (reference :891)."""

    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return _to_np(src).astype(self.typ)


def CreateAugmenter(
    data_shape,
    resize=0,
    rand_crop=False,
    rand_resize=False,
    rand_mirror=False,
    mean=None,
    std=None,
    brightness=0,
    contrast=0,
    saturation=0,
    hue=0,
    pca_noise=0,
    rand_gray=0,
    inter_method=2,
):
    """Builds the standard augmentation list (reference :903)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.08, (3.0 / 4.0, 4.0 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array(
            [
                [-0.5675, 0.7192, 0.4009],
                [-0.5808, -0.0045, -0.8140],
                [-0.5836, -0.6948, 0.4203],
            ]
        )
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = np.asarray(mean)
        assert mean.shape[0] in (1, 3)
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = np.asarray(std)
        assert std.shape[0] in (1, 3)
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(io.DataIter):
    """Flexible Python-side image iterator (reference :1017).

    Sources: ``path_imgrec`` (.rec file) or ``imglist`` + ``path_root``
    (list of [label, relpath]).  Applies ``aug_list`` augmenters per image and
    yields NCHW float32 batches.
    """

    def __init__(
        self,
        batch_size,
        data_shape,
        label_width=1,
        path_imgrec=None,
        path_imglist=None,
        path_root=None,
        shuffle=False,
        aug_list=None,
        imglist=None,
        data_name="data",
        label_name="softmax_label",
        last_batch_handle="pad",
        **kwargs,
    ):
        super().__init__(batch_size)
        assert len(data_shape) == 3 and data_shape[0] in (1, 3)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.data_name = data_name
        self.label_name = label_name
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.imgrec = None
        self.imglist = {}
        self.seq = []
        if path_imgrec is not None:
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.isfile(idx_path):
                self.imgrec = recordio.MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                # no index: load records into memory for shuffling support
                rec = recordio.MXRecordIO(path_imgrec, "r")
                self._records = []
                while True:
                    item = rec.read()
                    if item is None:
                        break
                    self._records.append(item)
                rec.close()
                self.seq = list(range(len(self._records)))
        elif path_imglist is not None or imglist is not None:
            if path_imglist is not None:
                imglist = []
                with open(path_imglist) as fin:
                    for line in fin:
                        parts = line.strip().split("\t")
                        if len(parts) < 3:
                            continue
                        imglist.append([float(x) for x in parts[1:-1]] + [parts[-1]])
            for i, entry in enumerate(imglist):
                label = np.asarray(entry[:-1], dtype=np.float32)
                self.imglist[i] = (label, entry[-1])
                self.seq.append(i)
            self.path_root = path_root or "."
        else:
            raise MXNetError("either path_imgrec, path_imglist, or imglist is required")
        if not self.seq:
            raise MXNetError("empty image source")
        if aug_list is None:
            aug_list = CreateAugmenter(data_shape, **kwargs)
        self.auglist = aug_list
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [io.DataDesc(self.data_name, (self.batch_size,) + self.data_shape, np.float32)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else (self.batch_size, self.label_width)
        return [io.DataDesc(self.label_name, shape, np.float32)]

    def reset(self):
        if self.shuffle:
            pyrandom.shuffle(self.seq)
        self.cur = 0

    def next_sample(self):
        """Returns (label, raw image array HWC uint8)."""
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.imgrec is not None:
            s = self.imgrec.read_idx(idx)
            header, img = recordio.unpack_img(s)
            label = header.label
            return label, img
        if hasattr(self, "_records"):
            header, img = recordio.unpack_img(self._records[idx])
            return header.label, img
        label, fname = self.imglist[idx]
        return label, imread(os.path.join(self.path_root, fname))

    def _aug(self, img):
        for aug in self.auglist:
            img = aug(img)
        return img

    def next(self):
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), dtype=np.float32)
        label = np.zeros((self.batch_size, self.label_width), dtype=np.float32)
        i = 0
        try:
            while i < self.batch_size:
                lab, img = self.next_sample()
                img = self._aug(img)
                if img.shape[:2] != (h, w):
                    raise MXNetError(
                        "augmented image shape %s does not match data_shape %s; "
                        "add a crop/resize augmenter" % (img.shape, self.data_shape)
                    )
                if img.ndim == 2:
                    img = img[..., None]
                data[i] = img.astype(np.float32).transpose(2, 0, 1)[:c]
                lab = np.atleast_1d(np.asarray(lab, dtype=np.float32))
                label[i, : min(self.label_width, lab.size)] = lab[: self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
            if self.last_batch_handle == "discard":
                raise
        pad = self.batch_size - i
        lab_out = label[:, 0] if self.label_width == 1 else label
        return io.DataBatch(
            data=[array(data)],
            label=[array(lab_out)],
            pad=pad,
            provide_data=self.provide_data,
            provide_label=self.provide_label,
        )
