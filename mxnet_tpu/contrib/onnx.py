"""ONNX import — reference ``python/mxnet/contrib/onnx/`` (import_model).

The `onnx` package is not available in this environment; the API surface is
kept so callers get an actionable error instead of an AttributeError."""
from __future__ import annotations


def import_model(model_file):
    """Imports an ONNX model file as (sym, arg_params, aux_params)
    (reference contrib/onnx/_import/import_model.py)."""
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "ONNX support requires the `onnx` package, which is not installed "
            "in this environment. Convert the model offline or install onnx."
        ) from e
    raise NotImplementedError(
        "ONNX graph translation to mxnet_tpu symbols is not implemented yet; "
        "file an issue with the opset you need.")
