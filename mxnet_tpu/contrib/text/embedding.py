"""Token embeddings — reference ``python/mxnet/contrib/text/embedding.py``
(registry :39, _TokenEmbedding :132, GloVe :468, FastText :558,
CustomEmbedding :658, CompositeEmbedding :719).

Zero-egress environment: the pretrained GloVe/FastText downloads are
unavailable; those classes load from a LOCAL file path via
``pretrained_file_path`` (same text format), and ``CustomEmbedding`` is the
primary entry point.
"""
from __future__ import annotations

import io
import logging
import os

import numpy as np

from ... import ndarray as nd
from .vocab import Vocabulary

_REGISTRY = {}


def register(embedding_cls):
    """Registers a new token embedding class (reference embedding.py:39)."""
    _REGISTRY[embedding_cls.__name__.lower()] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """Creates a registered embedding by name (reference :62)."""
    name = embedding_name.lower()
    if name not in _REGISTRY:
        raise KeyError(
            "Cannot find `embedding_name` %s. Use get_pretrained_file_names()."
            % embedding_name)
    return _REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Names of registered embeddings / their known files (reference :89)."""
    if embedding_name is not None:
        cls = _REGISTRY.get(embedding_name.lower())
        if cls is None:
            raise KeyError("Cannot find `embedding_name` %s" % embedding_name)
        return list(getattr(cls, "pretrained_file_names", []))
    return {name: list(getattr(cls, "pretrained_file_names", []))
            for name, cls in _REGISTRY.items()}


class _TokenEmbedding(Vocabulary):
    """Base embedding: token index + idx_to_vec matrix (reference :132)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    def _load_embedding(self, pretrained_file_path, elem_delim,
                        init_unknown_vec, encoding="utf8"):
        """Parses 'token v0 v1 ...' lines into the index + matrix
        (reference :231)."""
        pretrained_file_path = os.path.expanduser(pretrained_file_path)
        if not os.path.isfile(pretrained_file_path):
            raise ValueError(
                "`pretrained_file_path` must be a valid path to the "
                "pre-trained token embedding file (downloads are unavailable "
                "in this environment): %s" % pretrained_file_path)
        all_elems = []
        tokens = set()
        loaded_unknown_vec = None
        with io.open(pretrained_file_path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                elems = line.rstrip().split(elem_delim)
                assert len(elems) > 1, (
                    "line %d in %s: unexpected data format." % (line_num, pretrained_file_path))
                token, elems = elems[0], [float(i) for i in elems[1:]]
                if token == self.unknown_token and loaded_unknown_vec is None:
                    loaded_unknown_vec = elems
                elif token in tokens:
                    logging.warning("line %d in %s: duplicate embedding found for token %s. "
                                    "Skipped.", line_num, pretrained_file_path, token)
                elif len(elems) == 1:
                    logging.warning("line %d in %s: token %s with 1-dimensional vector %s; "
                                    "likely a header and skipped.",
                                    line_num, pretrained_file_path, token, elems)
                else:
                    if self._vec_len == 0:
                        self._vec_len = len(elems)
                    else:
                        assert len(elems) == self._vec_len, (
                            "line %d in %s: found vector of inconsistent dimension for token "
                            "%s. expected: %d, found: %d"
                            % (line_num, pretrained_file_path, token, self._vec_len, len(elems)))
                    all_elems.extend(elems)
                    self._idx_to_token.append(token)
                    self._token_to_idx[token] = len(self._idx_to_token) - 1
                    tokens.add(token)
        mat = np.zeros((len(self._idx_to_token), self._vec_len), dtype=np.float32)
        # rows before `base` are unknown + reserved tokens, not file rows
        base = len(self._idx_to_token) - (len(all_elems) // self._vec_len if self._vec_len else 0)
        if self._vec_len:
            mat[base:] = np.asarray(all_elems, dtype=np.float32).reshape(-1, self._vec_len)
        if loaded_unknown_vec is None:
            v = init_unknown_vec(shape=self._vec_len)
            unk = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
        else:
            unk = np.asarray(loaded_unknown_vec, dtype=np.float32)
        mat[:base] = unk  # unknown + reserved rows share the unknown init
        self._idx_to_vec = nd.array(mat)

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vectors for token(s) (reference :365)."""
        to_reduce = False
        if not isinstance(tokens, list):
            tokens = [tokens]
            to_reduce = True
        if not lower_case_backup:
            indices = [self.token_to_idx.get(t, 0) for t in tokens]
        else:
            indices = [
                self.token_to_idx[t] if t in self.token_to_idx
                else self.token_to_idx.get(t.lower(), 0) for t in tokens
            ]
        vecs = nd.take(self.idx_to_vec, nd.array(np.asarray(indices, np.int32)))
        return vecs[0] if to_reduce else vecs

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors of known tokens (reference :404)."""
        assert self.idx_to_vec is not None, "The property `idx_to_vec` has not been properly set."
        if not isinstance(tokens, list) or len(tokens) == 1:
            assert not isinstance(new_vectors, list), \
                "`new_vectors` must be an NDArray for one token."
            if not isinstance(tokens, list):
                tokens = [tokens]
            new_vectors = new_vectors.reshape((1, -1))
        indices = []
        for token in tokens:
            if token in self.token_to_idx:
                indices.append(self.token_to_idx[token])
            else:
                raise ValueError("Token %s is unknown; to update the unknown-token vector, "
                                 "use `%s` explicitly." % (token, self.unknown_token))
        mat = np.array(self.idx_to_vec.asnumpy())  # asnumpy view is read-only
        mat[np.asarray(indices)] = new_vectors.asnumpy()
        self._idx_to_vec = nd.array(mat)

    def _index_tokens_from_vocabulary(self, vocabulary):
        self._token_to_idx = vocabulary.token_to_idx.copy() \
            if vocabulary.token_to_idx is not None else None
        self._idx_to_token = vocabulary.idx_to_token[:] \
            if vocabulary.idx_to_token is not None else None
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = vocabulary.reserved_tokens[:] \
            if vocabulary.reserved_tokens is not None else None

    def _set_idx_to_vec_by_embeddings(self, token_embeddings, vocab_len, vocab_idx_to_token):
        """Lay out this vocabulary's matrix from source embeddings
        (reference :313)."""
        new_vec_len = sum(e.vec_len for e in token_embeddings)
        rows = np.zeros((vocab_len, new_vec_len), dtype=np.float32)
        col_start = 0
        for emb in token_embeddings:
            col_end = col_start + emb.vec_len
            rows[:, col_start:col_end] = emb.get_vecs_by_tokens(vocab_idx_to_token).asnumpy()
            col_start = col_end
        self._vec_len = new_vec_len
        self._idx_to_vec = nd.array(rows)

    def _build_embedding_for_vocabulary(self, vocabulary):
        """Re-key this embedding onto *vocabulary*: vectors are gathered with
        the CURRENT (file-order) mapping FIRST, then the index is swapped
        (reference :344 does exactly this order — reversing it reads wrong
        rows)."""
        if vocabulary is None:
            return
        assert isinstance(vocabulary, Vocabulary), \
            "`vocabulary` must be an instance of Vocabulary."
        new_vecs = self.get_vecs_by_tokens(vocabulary.idx_to_token).asnumpy()
        self._index_tokens_from_vocabulary(vocabulary)
        self._idx_to_vec = nd.array(new_vecs)


@register
class CustomEmbedding(_TokenEmbedding):
    """Embedding from a user file of 'token<delim>v0<delim>v1...' lines
    (reference embedding.py:658)."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim, init_unknown_vec, encoding)
        self._build_embedding_for_vocabulary(vocabulary)


@register
class GloVe(_TokenEmbedding):
    """GloVe text format (reference :468). Provide the local file via
    ``pretrained_file_path`` — downloads are unavailable here."""

    pretrained_file_names = [
        "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
        "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt",
        "glove.twitter.27B.25d.txt", "glove.twitter.27B.50d.txt",
        "glove.twitter.27B.100d.txt", "glove.twitter.27B.200d.txt",
    ]

    def __init__(self, pretrained_file_name="glove.840B.300d.txt",
                 embedding_root=None, init_unknown_vec=nd.zeros,
                 vocabulary=None, pretrained_file_path=None, **kwargs):
        super().__init__(**kwargs)
        if pretrained_file_path is None:
            root = embedding_root or os.path.join("~", ".mxnet", "embeddings", "glove")
            pretrained_file_path = os.path.join(root, pretrained_file_name)
        self._load_embedding(pretrained_file_path, " ", init_unknown_vec)
        self._build_embedding_for_vocabulary(vocabulary)


@register
class FastText(_TokenEmbedding):
    """fastText .vec text format (reference :558). Provide the local file via
    ``pretrained_file_path`` — downloads are unavailable here."""

    pretrained_file_names = ["wiki.en.vec", "wiki.simple.vec", "wiki.zh.vec"]

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=None, init_unknown_vec=nd.zeros,
                 vocabulary=None, pretrained_file_path=None, **kwargs):
        super().__init__(**kwargs)
        if pretrained_file_path is None:
            root = embedding_root or os.path.join("~", ".mxnet", "embeddings", "fasttext")
            pretrained_file_path = os.path.join(root, pretrained_file_name)
        self._load_embedding(pretrained_file_path, " ", init_unknown_vec)
        self._build_embedding_for_vocabulary(vocabulary)


class CompositeEmbedding(_TokenEmbedding):
    """Concatenation of several embeddings over one vocabulary
    (reference :719)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, list):
            token_embeddings = [token_embeddings]
        self._index_tokens_from_vocabulary(vocabulary)
        self._vec_len = 0
        self._idx_to_vec = None
        self._set_idx_to_vec_by_embeddings(
            token_embeddings, len(self), self.idx_to_token)
