"""Token-index vocabulary — reference ``python/mxnet/contrib/text/vocab.py:30``
(Vocabulary: counter-driven indexing, unknown/reserved tokens)."""
from __future__ import annotations

import collections

C_UNKNOWN_IDX = 0


class Vocabulary:
    """Indexes text tokens by frequency (reference vocab.py:79).

    Parameters mirror the reference: ``counter`` (collections.Counter or
    None), ``most_freq_count``, ``min_freq``, ``unknown_token``,
    ``reserved_tokens``.
    """

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        assert min_freq > 0, "`min_freq` must be set to a positive value."
        self._index_unknown_and_reserved_tokens(unknown_token, reserved_tokens)
        if counter is not None:
            self._index_counter_keys(counter, unknown_token, reserved_tokens,
                                     most_freq_count, min_freq)

    def _index_unknown_and_reserved_tokens(self, unknown_token, reserved_tokens):
        self._unknown_token = unknown_token
        if reserved_tokens is None:
            self._reserved_tokens = None
            self._idx_to_token = [unknown_token]
        else:
            reserved = list(reserved_tokens)
            assert unknown_token not in reserved, \
                "`reserved_tokens` cannot contain `unknown_token`."
            assert len(set(reserved)) == len(reserved), \
                "`reserved_tokens` cannot contain duplicate reserved tokens."
            self._reserved_tokens = reserved
            self._idx_to_token = [unknown_token] + reserved
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

    def _index_counter_keys(self, counter, unknown_token, reserved_tokens,
                            most_freq_count, min_freq):
        assert isinstance(counter, collections.Counter), \
            "`counter` must be an instance of collections.Counter."
        unknown_and_reserved = set(self._idx_to_token)
        token_freqs = sorted(counter.items(), key=lambda x: x[0])
        token_freqs.sort(key=lambda x: x[1], reverse=True)
        token_cap = len(unknown_and_reserved) + (
            len(counter) if most_freq_count is None else most_freq_count)
        for token, freq in token_freqs:
            if freq < min_freq or len(self._idx_to_token) == token_cap:
                break
            if token not in unknown_and_reserved:
                self._idx_to_token.append(token)
                self._token_to_idx[token] = len(self._idx_to_token) - 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token (or list of tokens) -> index/indices (reference :160)."""
        to_reduce = False
        if not isinstance(tokens, list):
            tokens = [tokens]
            to_reduce = True
        indices = [self.token_to_idx.get(t, C_UNKNOWN_IDX) for t in tokens]
        return indices[0] if to_reduce else indices

    def to_tokens(self, indices):
        """Index (or list) -> token(s) (reference :186)."""
        to_reduce = False
        if not isinstance(indices, list):
            indices = [indices]
            to_reduce = True
        max_idx = len(self._idx_to_token) - 1
        tokens = []
        for idx in indices:
            if not isinstance(idx, int) or not 0 <= idx <= max_idx:
                raise ValueError("Token index %s in the provided `indices` is invalid." % idx)
            tokens.append(self._idx_to_token[idx])
        return tokens[0] if to_reduce else tokens
