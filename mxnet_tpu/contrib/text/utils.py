"""Text tokenization helpers — reference
``python/mxnet/contrib/text/utils.py`` (count_tokens_from_str :~30)."""
from __future__ import annotations

import collections
import re


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Counts tokens in a (possibly multi-line) string (reference utils.py).

    Splits on token_delim/seq_delim, optionally lowercases, updates or
    creates a collections.Counter.
    """
    source_str = filter(
        None, re.split(re.escape(token_delim) + "|" + re.escape(seq_delim), source_str))
    if to_lower:
        source_str = [t.lower() for t in source_str]
    if counter_to_update is None:
        return collections.Counter(source_str)
    counter_to_update.update(source_str)
    return counter_to_update
