"""Model quantization — reference ``python/mxnet/contrib/quantization.py``
(quantize_model :405, _quantize_symbol :75, _quantize_params,
_get_optimal_threshold :253 [TensorRT-style KL calibration],
_LayerOutputMinMaxCollector :144) and the graph rewrite pass
``src/operator/quantization/quantize_graph_pass.cc``.

TPU-native: the rewrite is a pure Python pass over the Symbol DAG (no C++
pass manager needed — the graph is tiny); quantized kernels are int8→int32
MXU ops (ops/quantization.py). Flow:

    qsym, qargs, aux = quantize_model(sym, arg_params, aux_params,
                                      calib_mode='naive', calib_data=it)
"""
from __future__ import annotations

import logging

import numpy as np

from .. import ndarray as nd
from .. import symbol as _sym
from ..symbol import Symbol

__all__ = ["quantize_model"]

_QUANTIZABLE = {"Convolution", "FullyConnected"}
_PASSTHROUGH = {"Pooling", "Flatten"}


def _runtime_minmax(s, name):
    return _sym.min(s, name=name + "_min"), _sym.max(s, name=name + "_max")


class _Rewriter:
    """One _quantize_symbol run (reference quantize_graph_pass.cc)."""

    def __init__(self, excluded, offline, out_type):
        self.excluded = excluded
        self.offline = offline
        self.out_type = out_type
        self.fmap = {}  # (base_name, out_idx) -> float-domain Symbol
        self.qmap = {}  # base_name -> (q, mn, mx) triple in quantized domain
        self.deq_cache = {}

    def fval(self, inp):
        base = inp._base()
        idx = inp.out_index or 0
        key = (base.name, idx)
        if key in self.fmap:
            return self.fmap[key]
        if base.name in self.qmap:  # only quantized form exists: dequantize
            ck = (base.name, idx, "deq")
            if ck not in self.deq_cache:
                q, mn, mx = self.qmap[base.name]
                self.deq_cache[ck] = _sym.contrib.dequantize(
                    q, mn, mx, name=base.name + "_dequantize"
                )
            return self.deq_cache[ck]
        return inp  # untouched original (variable)

    def qval(self, inp):
        """Quantized-domain triple for an input, inserting quantize ops /
        offline-quantized param variables as needed."""
        base = inp._base()
        if base.name in self.qmap:
            return self.qmap[base.name]
        if base.is_var and base.name in self.offline:
            q = _sym.Variable(base.name + "_quantize")
            q._dtype_hint = "int8"  # simple_bind allocates the arg as int8
            mn = _sym.Variable(base.name + "_quantize_min")
            mx = _sym.Variable(base.name + "_quantize_max")
            self.qmap[base.name] = (q, mn, mx)
            return self.qmap[base.name]
        f = self.fval(inp)
        fmn, fmx = _runtime_minmax(f, base.name)
        out_type = self.out_type if base.is_var else "int8"
        trip = _sym.contrib.quantize(
            f, fmn, fmx, out_type=out_type, name=base.name + "_quantize"
        )
        self.qmap[base.name] = (trip[0], trip[1], trip[2])
        return self.qmap[base.name]

    def visit(self, node):
        if node.is_var:
            self.fmap[(node.name, 0)] = node
            return
        opname = node.op.name
        if opname in _QUANTIZABLE and node.name not in self.excluded:
            self._rewrite_quantizable(node)
        elif opname in _PASSTHROUGH and node.inputs and \
                node.inputs[0]._base().name in self.qmap and node.name not in self.excluded:
            self._rewrite_passthrough(node)
        elif opname == "Activation" and node.attrs.get("act_type", "relu") == "relu" \
                and node.inputs and node.inputs[0]._base().name in self.qmap \
                and node.name not in self.excluded:
            q, mn, mx = self.qmap[node.inputs[0]._base().name]
            trip = _sym.contrib.quantized_act(q, mn, mx, act_type="relu", name=node.name)
            self.qmap[node.name] = (trip[0], trip[1], trip[2])
        else:
            new_inputs = [self.fval(i) for i in node.inputs]
            rebuilt = Symbol(node.op, new_inputs, dict(node.attrs), node.name, node.num_outputs)
            for i in range(node.num_outputs):
                self.fmap[(node.name, i)] = rebuilt[i] if node.num_outputs > 1 else rebuilt

    def _rewrite_quantizable(self, node):
        attrs = dict(node.attrs)
        qd, mnd, mxd = self.qval(node.inputs[0])
        qw, mnw, mxw = self.qval(node.inputs[1])
        # keyword-wire the tensor args: inputs_fn drops bias from the middle
        # of the positional list when no_bias, so positions cannot be trusted
        tensor_kw = dict(
            data=qd, weight=qw, min_data=mnd, max_data=mxd,
            min_weight=mnw, max_weight=mxw,
        )
        no_bias = attrs.get("no_bias", False)
        if not no_bias and len(node.inputs) > 2:
            qb, mnb, mxb = self.qval(node.inputs[2])
            tensor_kw.update(bias=qb, min_bias=mnb, max_bias=mxb)
        fn = (
            _sym.contrib.quantized_conv
            if node.op.name == "Convolution"
            else _sym.contrib.quantized_fully_connected
        )
        out = fn(name=node.name + "_quantize", **tensor_kw, **attrs)
        req = _sym.contrib.requantize(
            out[0], out[1], out[2], name=node.name + "_requantize"
        )
        self.qmap[node.name] = (req[0], req[1], req[2])

    def _rewrite_passthrough(self, node):
        q, mn, mx = self.qmap[node.inputs[0]._base().name]
        if node.op.name == "Pooling":
            trip = _sym.contrib.quantized_pooling(q, mn, mx, name=node.name, **dict(node.attrs))
        else:
            trip = _sym.contrib.quantized_flatten(q, mn, mx, name=node.name)
        self.qmap[node.name] = (trip[0], trip[1], trip[2])


def _quantize_symbol(sym, excluded_symbols=None, offline_params=None,
                     quantized_dtype="int8"):
    """Rewrite a float Symbol into its quantized counterpart (reference
    contrib/quantization.py:75 over quantize_graph_pass.cc)."""
    excluded = {s._base().name for s in (excluded_symbols or [])}
    offline = set(offline_params or [])
    rw = _Rewriter(excluded, offline, quantized_dtype)
    for node in sym._walk():
        rw.visit(node)
    outs = []
    for head, idx in sym._outputs_of():
        outs.append(rw.fval(head))
    return outs[0] if len(outs) == 1 else _sym.Group(outs)


def _quantize_params(qsym, params):
    """Offline-quantize parameters consumed as ``*_quantize`` by the rewritten
    graph (reference _quantize_params)."""
    quantized_params = {}
    args = set(qsym.list_arguments())
    for name in args:
        if name.endswith("_quantize"):
            original = name[: -len("_quantize")]
            param = params[original]
            val = param.asnumpy()
            vmin, vmax = float(val.min()), float(val.max())
            q, mn, mx = nd.contrib.quantize(
                nd.array(val), nd.array([vmin]), nd.array([vmax]), out_type="int8"
            )
            quantized_params[name] = q
            quantized_params[name + "_min"] = mn
            quantized_params[name + "_max"] = mx
        elif name in params:
            quantized_params[name] = params[name]
    return quantized_params


def _calibrate_quantized_sym(qsym, th_dict):
    """Attach calibrated ranges to requantize nodes (reference
    _calibrate_quantized_sym :173)."""
    memo = {}

    def rebuild(s):
        if s.is_group:
            return _sym.Group([rebuild(i) for i in s.inputs])
        base = s._base()
        if base.name in memo:
            new_base = memo[base.name]
        else:
            if base.is_var:
                new_base = base
            else:
                new_inputs = [rebuild(i) for i in base.inputs]
                attrs = dict(base.attrs)
                if base.op.name == "_contrib_requantize":
                    layer = base.name[: -len("_requantize")] + "_output"
                    if layer in th_dict:
                        mn, mx = th_dict[layer]
                        attrs["min_calib_range"] = float(mn)
                        attrs["max_calib_range"] = float(mx)
                new_base = Symbol(base.op, new_inputs, attrs, base.name, base.num_outputs)
            memo[base.name] = new_base
        if s.out_index is not None and new_base.num_outputs > 1:
            return new_base[s.out_index]
        return new_base

    return rebuild(qsym)


def _collect_layer_output_min_max(mod, data_iter, include_layer=None,
                                  max_num_examples=None, logger=None):
    """Run forward over calibration data collecting per-layer (min, max)
    (reference _LayerOutputMinMaxCollector :144)."""
    th_dict = {}
    num = 0
    for batch in data_iter:
        outs = mod.predict_internals(batch)
        for name, arr in outs.items():
            if include_layer is not None and not include_layer(name):
                continue
            v = arr.asnumpy()
            mn, mx = float(v.min()), float(v.max())
            if name in th_dict:
                th_dict[name] = (min(th_dict[name][0], mn), max(th_dict[name][1], mx))
            else:
                th_dict[name] = (mn, mx)
        num += batch.data[0].shape[0]
        if max_num_examples is not None and num >= max_num_examples:
            break
    return th_dict, num


def _smooth_distribution(p, eps=0.0001):
    """(reference :234; Shannon-entropy smoothing for KL calibration)."""
    is_zeros = (p == 0).astype(np.float32)
    is_nonzeros = (p != 0).astype(np.float32)
    n_zeros = is_zeros.sum()
    n_nonzeros = p.size - n_zeros
    if not n_nonzeros:
        raise ValueError("The discrete probability distribution is malformed. All entries are 0.")
    eps1 = eps * float(n_zeros) / float(n_nonzeros)
    assert eps1 < 1.0, "n_zeros=%d, n_nonzeros=%d, eps1=%f" % (n_zeros, n_nonzeros, eps1)
    hist = p.astype(np.float32)
    hist += eps * is_zeros + (-eps1) * is_nonzeros
    assert (hist <= 0).sum() == 0
    return hist


def _kl_divergence(p, q):
    p = p / p.sum()
    q = q / q.sum()
    mask = p > 0
    return float(np.sum(p[mask] * np.log(p[mask] / np.maximum(q[mask], 1e-12))))


def _get_optimal_threshold(arr, num_bins=8001, num_quantized_bins=255):
    """KL-minimizing threshold (reference :253; 8-bit TensorRT calibration)."""
    arr = np.asarray(arr).ravel()
    th = max(abs(float(arr.min())), abs(float(arr.max())))
    if th == 0:
        return 0.0, 0.0, 0.0, 0.0
    hist, hist_edges = np.histogram(arr, bins=num_bins, range=(-th, th))
    zero_bin_idx = num_bins // 2
    num_half_quantized_bins = num_quantized_bins // 2

    thresholds = np.zeros(num_bins // 2 + 1 - num_quantized_bins // 2)
    divergence = np.zeros_like(thresholds)
    for i in range(num_quantized_bins // 2, num_bins // 2 + 1):
        p_bin_idx_start = zero_bin_idx - i
        p_bin_idx_stop = zero_bin_idx + i + 1
        thresholds[i - num_half_quantized_bins] = hist_edges[p_bin_idx_stop]
        sliced_nd_hist = hist[p_bin_idx_start:p_bin_idx_stop].astype(np.float64)

        p = sliced_nd_hist.copy()
        left_outlier_count = np.sum(hist[0:p_bin_idx_start])
        p[0] += left_outlier_count
        right_outlier_count = np.sum(hist[p_bin_idx_stop:])
        p[-1] += right_outlier_count
        is_nonzeros = (p != 0).astype(np.int32)

        num_merged_bins = sliced_nd_hist.size // num_quantized_bins
        quantized_bins = np.zeros(num_quantized_bins)
        for j in range(num_quantized_bins):
            start = j * num_merged_bins
            stop = start + num_merged_bins
            quantized_bins[j] = sliced_nd_hist[start:stop].sum()
        quantized_bins[-1] += sliced_nd_hist[num_quantized_bins * num_merged_bins:].sum()

        q = np.zeros(sliced_nd_hist.size, dtype=np.float64)
        for j in range(num_quantized_bins):
            start = j * num_merged_bins
            stop = q.size if j == num_quantized_bins - 1 else start + num_merged_bins
            norm = is_nonzeros[start:stop].sum()
            if norm != 0:
                q[start:stop] = float(quantized_bins[j]) / float(norm)
        q[p == 0] = 0
        try:
            p = _smooth_distribution(p)
            q = _smooth_distribution(q)
        except ValueError:
            divergence[i - num_half_quantized_bins] = float("inf")
            continue
        divergence[i - num_half_quantized_bins] = _kl_divergence(p, q)

    min_divergence_idx = int(np.argmin(divergence))
    opt_th = thresholds[min_divergence_idx]
    return float(arr.min()), float(arr.max()), float(divergence[min_divergence_idx]), float(opt_th)


def _get_optimal_thresholds(nd_dict, logger=None):
    th_dict = {}
    for name, arrs in nd_dict.items():
        flat = np.concatenate([a.ravel() for a in arrs])
        _, _, _, opt_th = _get_optimal_threshold(flat)
        th_dict[name] = (-opt_th, opt_th)
        if logger is not None:
            logger.debug("layer=%s th=%f" % (name, opt_th))
    return th_dict


def _collect_layer_outputs(mod, data_iter, include_layer=None,
                           max_num_examples=None, logger=None):
    nd_dict = {}
    num = 0
    for batch in data_iter:
        outs = mod.predict_internals(batch)
        for name, arr in outs.items():
            if include_layer is not None and not include_layer(name):
                continue
            nd_dict.setdefault(name, []).append(arr.asnumpy())
        num += batch.data[0].shape[0]
        if max_num_examples is not None and num >= max_num_examples:
            break
    return nd_dict, num


class _InternalsRunner:
    """Binds sym.get_internals() once and yields name->NDArray per batch
    (replaces the reference's Module + output-collector monkeypatching)."""

    def __init__(self, sym, arg_params, aux_params, data_names):
        self.internals = sym.get_internals()
        self.names = self.internals.list_outputs()
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.data_names = data_names
        self.exe = None
        self.shapes = None

    def predict_internals(self, batch):
        shapes = {n: d.shape for n, d in zip(self.data_names, batch.data)}
        if self.exe is None or shapes != self.shapes:
            self.shapes = shapes
            self.exe = self.internals.simple_bind(grad_req="null", **shapes)
            for k, v in self.arg_params.items():
                if k in self.exe.arg_dict:
                    self.exe.arg_dict[k][:] = v
            for k, v in self.aux_params.items():
                if k in self.exe.aux_dict:
                    self.exe.aux_dict[k][:] = v
        feed = {n: d for n, d in zip(self.data_names, batch.data)}
        outs = self.exe.forward(is_train=False, **feed)
        return dict(zip(self.names, outs))


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, calib_mode="entropy",
                   calib_data=None, num_calib_examples=None, calib_layer=None,
                   quantized_dtype="int8", logger=logging):
    """Generate an int8 model from an fp32 model, optionally calibrated
    (reference contrib/quantization.py:405)."""
    if excluded_sym_names is None:
        excluded_sym_names = []
    if not isinstance(excluded_sym_names, list):
        raise ValueError("excluded_sym_names must be a list of strings")
    if quantized_dtype not in ("int8", "uint8"):
        raise ValueError("unknown quantized_dtype %s, expected int8 or uint8"
                         % quantized_dtype)

    excluded_syms = []
    nodes = sym.get_internals()
    onames = nodes.list_outputs()
    for name in excluded_sym_names:
        idx = onames.index(name + "_output")
        excluded_syms.append(nodes[idx])

    qsym = _quantize_symbol(
        sym, excluded_symbols=excluded_syms,
        offline_params=list(arg_params.keys()), quantized_dtype=quantized_dtype,
    )
    qarg_params = _quantize_params(qsym, arg_params)

    if calib_mode is not None and calib_mode != "none":
        if calib_data is None:
            raise ValueError("calib_data must be provided when calib_mode=%s" % calib_mode)
        if calib_layer is None:
            calib_layer = lambda name: name.endswith("_output")
        runner = _InternalsRunner(sym, arg_params, aux_params, list(data_names))
        if calib_mode == "entropy":
            nd_dict, num = _collect_layer_outputs(
                runner, calib_data, include_layer=calib_layer,
                max_num_examples=num_calib_examples,
            )
            th_dict = _get_optimal_thresholds(nd_dict, logger=logger)
        elif calib_mode == "naive":
            th_dict, num = _collect_layer_output_min_max(
                runner, calib_data, include_layer=calib_layer,
                max_num_examples=num_calib_examples,
            )
        else:
            raise ValueError("unknown calibration mode %s, expected none/naive/entropy"
                             % calib_mode)
        qsym = _calibrate_quantized_sym(qsym, th_dict)

    return qsym, qarg_params, aux_params
