"""TensorBoard logging callback — reference
``python/mxnet/contrib/tensorboard.py:25`` (LogMetricsCallback).

The `tensorboard` package is optional; construction fails with a clear
message when it (or an equivalent SummaryWriter provider) is absent.
"""
from __future__ import annotations


class LogMetricsCallback:
    """Log training speedometer metrics to TensorBoard (reference :25).

    Usage mirrors the reference::

        lm = LogMetricsCallback('logs/train')
        mod.fit(..., batch_end_callback=[lm])
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        try:
            from tensorboard import SummaryWriter  # 2018-era package layout

            self.summary_writer = SummaryWriter(logging_dir)
        except ImportError:
            try:  # modern providers expose the same writer API
                from torch.utils.tensorboard import SummaryWriter

                self.summary_writer = SummaryWriter(logging_dir)
            except ImportError:
                raise ImportError(
                    "LogMetricsCallback requires a SummaryWriter provider "
                    "(`tensorboard` or `torch.utils.tensorboard`). "
                    "Install one or use mx.callback.Speedometer for console logs.")

    def __call__(self, param):
        """Callback to log metrics at batch end."""
        if param.eval_metric is None:
            return
        name_value = param.eval_metric.get_name_value()
        self.step += 1
        for name, value in name_value:
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            # explicit global_step: torch's writer defaults to step 0,
            # which would overwrite every point
            self.summary_writer.add_scalar(name, value, self.step)
