"""Legacy autograd API — reference ``python/mxnet/contrib/autograd.py``
(train_section :74, test_section :88, mark_variables :102, backward :123,
grad_and_loss :163, grad :195). Thin adapters over mxnet_tpu.autograd."""
from __future__ import annotations

import functools

from .. import autograd as _ag
from .. import ndarray as nd

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


def set_is_training(is_train):
    """Legacy global training-mode toggle (reference :32)."""
    prev = _ag.is_training()
    _ag.set_training(is_train)
    _ag.set_recording(is_train)
    return prev


class TrainingStateScope:
    """(reference :54)"""

    def __init__(self, enter_state):
        self._enter_state = enter_state
        self._prev_record = None
        self._prev_train = None

    def __enter__(self):
        self._prev_record = _ag.set_recording(True)
        self._prev_train = _ag.set_training(self._enter_state)

    def __exit__(self, ptype, value, trace):
        _ag.set_recording(self._prev_record)
        _ag.set_training(self._prev_train)


def train_section():
    """Scope: computation taped and in training mode (reference :74)."""
    return TrainingStateScope(True)


def test_section():
    """Scope: taped but inference mode (reference :88)."""
    return TrainingStateScope(False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """(reference :102)"""
    _ag.mark_variables(variables, gradients, grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    """(reference :123)"""
    _ag.backward(outputs, head_grads=out_grads, retain_graph=retain_graph)


def compute_gradient(outputs):
    """(reference :158)"""
    backward(outputs)


def grad_and_loss(func, argnum=None):
    """Returns fn computing both gradient of *func* and its loss
    (reference :163)."""

    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            argnums = [argnum] if isinstance(argnum, int) else list(argnum)
            variables = [args[i] for i in argnums]
        for x in variables:
            assert isinstance(x, nd.NDArray), "type of autograd input should be NDArray."
        grads = [nd.zeros_like(x) for x in variables]
        mark_variables(variables, grads)
        with train_section():
            outputs = func(*args)
        compute_gradient([outputs] if isinstance(outputs, nd.NDArray) else outputs)
        return grads, outputs

    return wrapped


def grad(func, argnum=None):
    """Returns fn computing gradient of *func* (reference :195)."""
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]

    return wrapped
