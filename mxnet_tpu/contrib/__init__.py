"""Contrib package — reference ``python/mxnet/contrib/`` (quantization,
autograd compat, text, onnx, tensorboard)."""
from . import quantization  # noqa: F401
from . import autograd  # noqa: F401
from . import text  # noqa: F401
from . import tensorboard  # noqa: F401
from . import onnx  # noqa: F401
