"""Contrib package — reference ``python/mxnet/contrib/`` (quantization,
autograd compat, text, onnx, tensorboard)."""
from . import quantization  # noqa: F401
