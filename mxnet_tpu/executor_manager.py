"""Legacy multi-device executor helpers (reference ``python/mxnet/executor_manager.py``).

The Module package is the maintained multi-device path; this keeps the legacy
``FeedForward``-era API importable: batch-slicing across contexts and a thin
``DataParallelExecutorManager`` driving one executor per context.  On TPU the
real data parallelism is a sharded jit (SURVEY §2.2) — these slices map to
per-device shards of the global batch.
"""
from __future__ import annotations

import logging

import numpy as np

from .base import MXNetError

__all__ = ["_split_input_slice", "_check_arguments", "DataParallelExecutorManager"]


def _split_input_slice(batch_size, work_load_list):
    """Split a batch into per-device slices proportional to work loads
    (reference executor_manager.py:33).  Returns a list of ``slice``s."""
    total = sum(work_load_list)
    if total <= 0:
        raise MXNetError("Invalid workload")
    batch_num_list = [round(batch_size * v / total) for v in work_load_list]
    # rounding remainder goes to the last slice so every sample is assigned
    batch_num_list[-1] += batch_size - sum(batch_num_list)
    slices = []
    end = 0
    for n in batch_num_list:
        begin = int(min(end, batch_size))
        end = int(min(begin + n, batch_size))
        if begin >= end:
            raise MXNetError("Too many slices. Some splits are empty.")
        slices.append(slice(begin, end))
    return slices


def _check_arguments(symbol):
    """Assert argument/aux names are unique (reference executor_manager.py:57)."""
    arg_names = symbol.list_arguments()
    if len(set(arg_names)) != len(arg_names):
        raise MXNetError(
            "Find duplicated argument name,"
            " please make the weight name non-duplicated, arg_names=%s" % str(arg_names)
        )
    aux_names = symbol.list_auxiliary_states()
    if len(set(aux_names)) != len(aux_names):
        raise MXNetError(
            "Find duplicated auxiliary param name, aux_names=%s" % str(aux_names)
        )


class DataParallelExecutorManager:
    """Legacy helper running one executor per context (reference :205).

    Delegates to per-context ``simple_bind`` executors; gradients are summed
    on host.  New code should use ``mxnet_tpu.module.Module`` (which compiles
    a single sharded step instead).
    """

    def __init__(
        self,
        symbol,
        ctx,
        train_data,
        arg_names=None,
        param_names=None,
        aux_names=None,
        work_load_list=None,
        logger=None,
        sym_gen=None,
    ):
        self.logger = logger or logging
        num_device = len(ctx)
        self.logger.info("Start training with %s", str(ctx))
        if work_load_list is None:
            work_load_list = [1] * num_device
        if len(work_load_list) != num_device:
            raise MXNetError("Invalid settings for work load.")
        self.symbol = symbol
        self.ctx = ctx
        self.slices = _split_input_slice(train_data.batch_size, work_load_list)
        self.arg_names = arg_names or symbol.list_arguments()
        self.aux_names = aux_names or symbol.list_auxiliary_states()
        data_names = [d.name for d in train_data.provide_data] + [
            l.name for l in train_data.provide_label
        ]
        self.param_names = param_names or [
            n for n in self.arg_names if n not in data_names
        ]
        _check_arguments(symbol)

        shapes = {d.name: d.shape for d in train_data.provide_data}
        shapes.update({l.name: l.shape for l in train_data.provide_label})
        self.execs = []
        for i, _ in enumerate(ctx):
            sl = self.slices[i]
            dev_shapes = {
                k: (sl.stop - sl.start,) + tuple(s[1:]) for k, s in shapes.items()
            }
            self.execs.append(symbol.simple_bind(ctx=ctx[i], grad_req="write", **dev_shapes))

    @property
    def param_arrays(self):
        return [
            [e.arg_dict[n] for e in self.execs] for n in self.param_names
        ]

    @property
    def grad_arrays(self):
        return [
            [e.grad_dict.get(n) for e in self.execs] for n in self.param_names
        ]

    @property
    def aux_arrays(self):
        return [[e.aux_dict[n] for e in self.execs] for n in self.aux_names]

    def install_monitor(self, monitor):
        for e in self.execs:
            monitor.install(e)

    def set_params(self, arg_params, aux_params):
        for e in self.execs:
            e.copy_params_from(arg_params, aux_params)

    def load_data_batch(self, data_batch):
        self._batch = data_batch

    def forward(self, is_train=False):
        from .ndarray import array

        for i, e in enumerate(self.execs):
            sl = self.slices[i]
            for desc, d in zip(self._batch.provide_data, self._batch.data):
                e.arg_dict[desc.name]._rebind(array(d.asnumpy()[sl])._data)
            for desc, l in zip(self._batch.provide_label, self._batch.label or []):
                e.arg_dict[desc.name]._rebind(array(l.asnumpy()[sl])._data)
            e.forward(is_train=is_train)

    def backward(self):
        for e in self.execs:
            e.backward()

    def update_metric(self, metric, labels):
        for i, e in enumerate(self.execs):
            sl = self.slices[i]
            labels_slice = [type(l)(l.asnumpy()[sl]) if hasattr(l, "asnumpy") else l[sl] for l in labels]
            metric.update(labels_slice, e.outputs)
