"""Profiler — chrome-trace profiling facade (reference ``python/mxnet/profiler.py``).

TPU-native design (SURVEY §5.1): the reference's lock-free per-device stat
queues (``src/profiler/profiler.h:256``, ``DeviceStats :223``) instrumented
every engine push; here the device-side story is XLA's own profiler
(``jax.profiler`` → TensorBoard XPlane traces), and this module provides

1. the reference's *user-annotation* object model — ``Domain``, ``Task``,
   ``Frame``, ``Event``, ``Counter``, ``Marker`` (reference
   ``profiler.py:151-240``, C++ ``ProfileDomain :528`` / ``ProfileCounter
   :556``) — recording into an in-process buffer, and
2. ``dump()`` emitting **chrome://tracing JSON** exactly like the reference's
   ``Profiler::DumpProfile`` (``src/profiler/profiler.h:304``), and
3. ``set_state('run')`` optionally starting a ``jax.profiler`` trace so the
   XLA/TPU timeline lands next to the user annotations.

Use ``mx.profiler.set_config(filename='profile.json'); set_state('run')``,
then open the dumped file in chrome://tracing or Perfetto.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = [
    "set_config",
    "profiler_set_config",
    "set_state",
    "profiler_set_state",
    "state",
    "pause",
    "resume",
    "dump",
    "dumps",
    "dump_profile",
    "Domain",
    "Task",
    "Frame",
    "Event",
    "Counter",
    "Marker",
]

_lock = threading.Lock()
_events = []  # chrome trace event dicts
_meta_events = []  # "ph":"M" metadata — recorded unconditionally (a Domain
# created before set_state('run') must still name its pid in the dump)
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": False,
    "continuous_dump": False,
    "use_xla_trace": False,  # also capture a jax.profiler trace dir
}
_state = "stop"
_paused = False
_xla_trace_dir = None
_t0 = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def _emit(ev):
    if _state != "run" or _paused:
        return
    with _lock:
        _events.append(ev)


def _emit_meta(ev):
    """Metadata events carry no timestamp and are state-independent: drop
    nothing, re-emit all of them in every dumps() (chrome://tracing needs the
    process_name record even when the domain predates set_state('run'))."""
    with _lock:
        _meta_events.append(ev)


class _AtomicValue:
    """Lock-guarded numeric cell — the shared thread-safe read-modify-write
    primitive behind profiler and telemetry counters (a bare ``self._value +=
    delta`` races: two threads can read the same base value and lose one
    increment)."""

    __slots__ = ("_mu", "_v")

    def __init__(self, value=0):
        self._mu = threading.Lock()
        self._v = value

    def add(self, delta):
        with self._mu:
            self._v += delta
            return self._v

    def set(self, value):
        with self._mu:
            self._v = value
            return self._v

    def get(self):
        with self._mu:
            return self._v


def _op_profiling_active():
    """Fast check for the eager frontend's per-op hook."""
    return (
        _state == "run"
        and not _paused
        and (_config["profile_imperative"] or _config["profile_all"])
    )


def _symbolic_profiling_active():
    """Per-forward/backward hook for the symbolic executor
    (reference profile_symbolic: GraphExecutor operator bracketing)."""
    return (
        _state == "run"
        and not _paused
        and (_config["profile_symbolic"] or _config["profile_all"])
    )


def _emit_op(name, t0_us, dur_us):
    """One operator execution (reference ThreadedEngine::ExecuteOprBlock
    bracketing, threaded_engine.h:335). Eager jax dispatch is async, so the
    duration covers trace+enqueue (and compile on first call) — the XLA
    device timeline comes from use_xla_trace."""
    _emit({
        "name": name,
        "cat": "operator",
        "ph": "X",
        "ts": t0_us,
        "dur": dur_us,
        "pid": 0,
        "tid": threading.get_ident() % 1_000_000,
    })


def set_config(**kwargs):
    """Configure the profiler (reference ``profiler.py:28`` set_config).

    Accepts the reference kwargs (``filename``, ``profile_all``,
    ``profile_symbolic``, ``profile_imperative``, ``profile_memory``,
    ``profile_api``, ``aggregate_stats``, ``continuous_dump``) plus
    ``use_xla_trace=True`` to also record a ``jax.profiler`` trace directory
    alongside the chrome-trace file.
    """
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise ValueError("unknown profiler config keys: %s" % sorted(unknown))
    _config.update(kwargs)


profiler_set_config = set_config


def state():
    return _state


def set_state(state="stop", profile_process="worker"):
    """'run' starts recording; 'stop' stops (and dumps if continuous_dump)."""
    global _state, _xla_trace_dir
    if state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    if state == "run" and _state != "run":
        _state = "run"
        if _config["use_xla_trace"]:
            import jax

            _xla_trace_dir = os.path.splitext(_config["filename"])[0] + "_xla"
            jax.profiler.start_trace(_xla_trace_dir)
    elif state == "stop" and _state == "run":
        if _config["use_xla_trace"] and _xla_trace_dir is not None:
            import jax

            jax.profiler.stop_trace()
            _xla_trace_dir = None
        _state = "stop"
        if _config["continuous_dump"]:
            dump()


profiler_set_state = set_state


def pause(profile_process="worker"):
    """Suspend recording without ending the run (reference MXProfilePause)."""
    global _paused
    _paused = True


def resume(profile_process="worker"):
    global _paused
    _paused = False


def dumps(reset=False):
    """Return the chrome-trace JSON string (reference aggregate dumps).

    Metadata events (process names) are re-emitted on every call and survive
    ``reset`` — they are declarations, not samples.  When the Pallas kernel
    module is loaded, its traced custom-call cost table rides along as one
    extra metadata record so ``tools/trace_summary.py`` can restore FLOPs and
    bytes for custom calls that XLA cost analysis cannot see.
    """
    with _lock:
        evs = list(_meta_events) + list(_events)
        if reset:
            _events.clear()
    # clock anchor (unix time ↔ this dump's trace timebase): lets
    # tools/trace_merge.py place these events and a telemetry/tracing span
    # export — or any other clock_sync-carrying trace — on one timeline
    evs.insert(0, {"name": "clock_sync", "ph": "M", "pid": 0,
                   "args": {"unix_ts": round(time.time(), 6),
                            "trace_ts_us": round(_now_us(), 3)}})
    import sys

    pk = sys.modules.get("mxnet_tpu.ops.pallas_kernels")
    if pk is not None:
        costs = pk.traced_costs()
        if costs:
            evs.insert(0, {"name": "custom_call_costs", "ph": "M", "pid": 0,
                           "args": costs})
    return json.dumps({"traceEvents": evs, "displayTimeUnit": "ms"}, indent=1)


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON to the configured filename."""
    data = dumps(reset=finished)
    with open(_config["filename"], "w") as f:
        f.write(data)
    return _config["filename"]


dump_profile = dump  # deprecated reference alias


class Domain:
    """Named grouping of profiler objects (reference ProfileDomain :528);
    becomes the chrome-trace process name."""

    _next_pid = [1]

    def __init__(self, name):
        self.name = name
        self.pid = Domain._next_pid[0]
        Domain._next_pid[0] += 1
        _emit_meta(
            {
                "name": "process_name",
                "ph": "M",
                "pid": self.pid,
                "args": {"name": name},
            }
        )

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)

    def __repr__(self):
        return "Domain('%s')" % self.name


_default_domain = None


def _domain_of(domain):
    global _default_domain
    if domain is not None:
        return domain
    if _default_domain is None:
        _default_domain = Domain("mxnet_tpu")
    return _default_domain


class _DurationObject:
    _phase = "X"
    _cat = "task"

    def __init__(self, domain, name):
        self.domain = _domain_of(domain)
        self.name = name
        self._start_us = None

    def start(self):
        self._start_us = _now_us()
        return self

    def stop(self):
        if self._start_us is None:
            return self
        _emit(
            {
                "name": self.name,
                "cat": self._cat,
                "ph": "X",
                "ts": self._start_us,
                "dur": _now_us() - self._start_us,
                "pid": self.domain.pid,
                "tid": threading.get_ident() % 1_000_000,
            }
        )
        self._start_us = None
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def __repr__(self):
        return "%s('%s')" % (type(self).__name__, self.name)


class Task(_DurationObject):
    """Generic start/stop work item bound to a domain (reference Task)."""

    _cat = "task"


class Frame(_DurationObject):
    """Per-iteration frame (reference Frame) — e.g. one training batch."""

    _cat = "frame"


class Event(_DurationObject):
    """Thread-bound duration event (reference Event); domain-less."""

    _cat = "event"

    def __init__(self, name):
        super().__init__(None, name)


class Counter:
    """Numeric time-series counter (reference ProfileCounter :556)."""

    def __init__(self, domain, name, value=None):
        self.domain = _domain_of(domain)
        self.name = name
        self._value = _AtomicValue(0)
        if value is not None:
            self.set_value(value)

    def _emit_sample(self, value):
        _emit(
            {
                "name": self.name,
                "ph": "C",
                "ts": _now_us(),
                "pid": self.domain.pid,
                "args": {self.name: value},
            }
        )

    def set_value(self, value):
        self._emit_sample(self._value.set(value))

    def increment(self, delta=1):
        # add() returns the post-update value, so the emitted sample cannot
        # observe a concurrent writer's torn intermediate state
        self._emit_sample(self._value.add(delta))

    def decrement(self, delta=1):
        self._emit_sample(self._value.add(-delta))

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    """Instant annotation (reference Marker); scope: 'process' or 'thread'."""

    def __init__(self, domain, name):
        self.domain = _domain_of(domain)
        self.name = name

    def mark(self, scope="process"):
        _emit(
            {
                "name": self.name,
                "ph": "i",
                "ts": _now_us(),
                "pid": self.domain.pid,
                "tid": threading.get_ident() % 1_000_000,
                "s": {"process": "p", "thread": "t", "global": "g"}.get(scope, "p"),
            }
        )
