"""mxnet_tpu — a TPU-native deep-learning framework with MXNet's capabilities.

Brand-new design on JAX/XLA/Pallas: the reference's threaded dependency engine
becomes XLA async dispatch; NNVM graph passes become jit tracing; CUDA kernels
become XLA ops + Pallas kernels; ps-lite KVStore becomes XLA collectives over
a device mesh.  See SURVEY.md at the repo root for the full blueprint.

Import surface mirrors ``import mxnet as mx``: mx.nd, mx.sym, mx.gluon,
mx.autograd, mx.init, mx.io, mx.kv, mx.metric, mx.mod, ...
"""
__version__ = "0.1.0"

from .base import MXNetError
from .context import Context, cpu, gpu, tpu, current_context, num_gpus
from . import base
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random

# seeded lazily to avoid importing jax at package import when unused
seed = random.seed

# AOT persistent executable cache (compile_cache.py, ISSUE 6): jax's
# persistent compilation cache latches its directory at the FIRST XLA
# compile in the process, so MXNET_AOT_CACHE must be applied at import,
# before anything can compile.  Unset ⇒ no-op, and jax is not imported.
import os as _os

if _os.environ.get("MXNET_AOT_CACHE", "").strip():
    from . import compile_cache as _compile_cache

    _compile_cache.activate()


def __getattr__(name):
    """Lazy submodule loading keeps `import mxnet_tpu` fast."""
    import importlib

    lazy = {
        "sym": ".symbol",
        "symbol": ".symbol",
        "gluon": ".gluon",
        "init": ".initializer",
        "initializer": ".initializer",
        "optimizer": ".optimizer",
        "metric": ".metric",
        "io": ".io",
        "kv": ".kvstore",
        "kvstore": ".kvstore",
        "mod": ".module",
        "module": ".module",
        "callback": ".callback",
        "lr_scheduler": ".lr_scheduler",
        "model": ".model",
        "name": ".name",
        "attribute": ".attribute",
        "autotune": ".autotune",
        "operator": ".operator",
        "rnn": ".rnn",
        "executor_manager": ".executor_manager",
        "viz": ".visualization",
        "profiler": ".profiler",
        "telemetry": ".telemetry",
        "recordio": ".recordio",
        "image": ".image",
        "test_utils": ".test_utils",
        "parallel": ".parallel",
        "executor": ".executor",
        "compile_cache": ".compile_cache",
        "monitor": ".monitor",
        "visualization": ".visualization",
        "contrib": ".contrib",
        "engine": ".engine",
        "rtc": ".rtc",
        "predictor": ".predictor",
        "serving": ".serving",
        "th": ".torch_bridge",
        "torch_bridge": ".torch_bridge",
    }
    if name in lazy:
        try:
            mod = importlib.import_module(lazy[name], __name__)
        except ImportError as e:
            # a missing OR broken optional dependency (torch absent, torch's
            # native extension failing to load, …) reads as "feature absent"
            # for hasattr()-style probes; an import failure originating in
            # one of our OWN submodules must surface loudly, not masquerade
            # as an absent feature
            if (getattr(e, "name", None) or "").split(".")[0] == __name__.split(".")[0]:
                raise
            raise AttributeError(
                "module %r has no attribute %r (%s)" % (__name__, name, e)
            ) from e
        globals()[name] = mod
        return mod
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
