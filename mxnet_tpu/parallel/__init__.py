"""Parallelism primitives — the TPU-native replacement for the reference's
device-placement + KVStore machinery (SURVEY §2.2, §5.8).

The reference scales by slicing batches across explicit device contexts
(``python/mxnet/module/executor_group.py:143``) and reducing gradients through
a KVStore backed by ps-lite / NCCL (``src/kvstore/``).  Here scaling is
declarative: pick a :class:`jax.sharding.Mesh`, annotate array shardings, and
XLA inserts the collectives over ICI/DCN.

Public surface:
- :func:`make_mesh` / :func:`current_mesh` — named device meshes (dp/tp/pp/sp/ep axes)
- :func:`shard` / :func:`replicate` — NamedSharding helpers
- :func:`allreduce` / :func:`allgather` — pytree collectives usable inside shard_map
- :mod:`mxnet_tpu.parallel.dist` — multi-host bootstrap (jax.distributed), the
  replacement for ``tools/launch.py`` + dmlc tracker roles
- :mod:`mxnet_tpu.parallel.ring` — ring attention (sequence/context parallelism)
"""
from .mesh import (
    make_mesh,
    current_mesh,
    default_mesh,
    set_default_mesh,
    shard,
    replicate,
    named_sharding,
    shard_params,
    local_mesh_devices,
    place_committed,
    zero_shard_spec,
    zero1_shardings,
    zero1_place,
    zero1_state_bytes,
    mesh_process_count,
    mesh_spans_processes,
    mesh_axis_local_size,
    mesh_axis_spans_processes,
    mesh_batch_factor,
    global_batch_array,
    host_local_rows,
)
from .collectives import (allreduce, allgather, reduce_scatter, pmean,
                          psum_scatter, note_derived)
from . import dist
from . import checkpoint
from .ring import ring_attention, ring_self_attention
from .pipeline import gpipe, stack_stage_params
from .moe import moe_ffn, stack_expert_params

__all__ = [
    "make_mesh",
    "current_mesh",
    "default_mesh",
    "set_default_mesh",
    "shard",
    "replicate",
    "named_sharding",
    "shard_params",
    "local_mesh_devices",
    "place_committed",
    "zero_shard_spec",
    "zero1_shardings",
    "zero1_place",
    "zero1_state_bytes",
    "mesh_process_count",
    "mesh_spans_processes",
    "mesh_axis_local_size",
    "mesh_axis_spans_processes",
    "mesh_batch_factor",
    "global_batch_array",
    "host_local_rows",
    "allreduce",
    "allgather",
    "reduce_scatter",
    "pmean",
    "psum_scatter",
    "note_derived",
    "dist",
    "checkpoint",
    "ring_attention",
    "ring_self_attention",
    "gpipe",
    "stack_stage_params",
    "moe_ffn",
    "stack_expert_params",
]
