"""Multi-host bootstrap — replaces ``tools/launch.py`` + dmlc tracker.

The reference spawns scheduler/server/worker roles over ssh/mpi/yarn and wires
them through ps-lite (``src/kvstore/kvstore_dist.h:50-55``, SURVEY §3.5).  The
TPU-native design has no parameter servers: every host is a worker, and
``jax.distributed.initialize`` + DCN collectives replace the tracker and RPC.

Environment contract (mirrors the reference's DMLC_* env protocol):
  MXNET_COORDINATOR  — "host:port" of process 0 (≡ scheduler address)
  MXNET_NUM_WORKERS  — total process count (≡ DMLC_NUM_WORKER)
  MXNET_WORKER_RANK  — this process's rank   (≡ DMLC_RANK)
Standard TPU-pod env (Cloud TPU metadata) is auto-detected by JAX when these
are absent, so on real pods ``init()`` with no args is enough.
"""
from __future__ import annotations

import os

_initialized = False


class DeadNodeError(RuntimeError):
    """A collective timed out because specific ranks never arrived.

    The reference detects dead nodes at barrier setup via the scheduler
    heartbeat (``ps::Postoffice::GetDeadNodes``, kvstore_dist.h:110-118) and
    aborts with the dead node list; without this, a lost rank silently hangs
    the whole job.  Carries ``missing_ranks``.
    """

    def __init__(self, barrier_name, missing_ranks, timeout_ms):
        self.missing_ranks = list(missing_ranks)
        super().__init__(
            "barrier %r timed out after %d ms: rank(s) %s never reported "
            "arrival (dead-node check over the coordination service) — the "
            "process(es) most likely died or hung; restart the job "
            "(reference semantics: checkpoint + relaunch, SURVEY §5.3)"
            % (barrier_name, timeout_ms,
               ",".join(str(r) for r in self.missing_ranks)))


def init(coordinator_address=None, num_processes=None, process_id=None,
         initialization_timeout=None, **kw):
    """Initialize multi-host JAX.  Idempotent; no-op in single-process runs
    unless coordinator env/args are present.

    ``initialization_timeout`` (seconds; env ``MXNET_DIST_INIT_TIMEOUT``)
    bounds the startup rendezvous — with a rank missing at launch the
    survivors fail after this timeout instead of waiting forever (the
    reference's scheduler barrier behaves the same way via heartbeat
    timeouts, kvstore_dist.h:110-118).  Note jax's distributed client
    TERMINATES the process on rendezvous timeout (fatal log, not a
    catchable exception) — fail-fast semantics, not recoverable ones."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("MXNET_COORDINATOR")
    if num_processes is None and "MXNET_NUM_WORKERS" in os.environ:
        num_processes = int(os.environ["MXNET_NUM_WORKERS"])
    if process_id is None and "MXNET_WORKER_RANK" in os.environ:
        process_id = int(os.environ["MXNET_WORKER_RANK"])
    if coordinator_address is None and num_processes is None:
        # single-host; jax.distributed not needed
        _initialized = True
        return
    if initialization_timeout is None and "MXNET_DIST_INIT_TIMEOUT" in os.environ:
        initialization_timeout = int(os.environ["MXNET_DIST_INIT_TIMEOUT"])
    if initialization_timeout is not None:
        kw["initialization_timeout"] = int(initialization_timeout)
    import jax

    # CPU backend: select the Gloo collectives implementation BEFORE the
    # backend instantiates — without one the CpuClient rejects every
    # process-spanning computation ("Multiprocess computations aren't
    # implemented on the CPU backend"), which would make the pod-mesh
    # paths (fused step + ZeRO over a 2-process fake cluster, orbax
    # collective saves) untestable off-TPU.  Gated on an explicit CPU
    # platform selection so real TPU/GPU pods are untouched; the flag
    # only affects CPU client creation.
    plats = (os.environ.get("JAX_PLATFORMS")
             or os.environ.get("JAX_PLATFORM_NAME") or "")
    if "cpu" in plats.split(","):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # older jax without the option: single-host tests only

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kw,
    )
    _initialized = True


def rank():
    """This host's index (reference ``KVStore.rank``, ``kvstore_dist.h:106``)."""
    import jax

    return jax.process_index()


def size():
    """Number of hosts (reference ``KVStore.num_workers``)."""
    import jax

    return jax.process_count()


def is_coordinator():
    return rank() == 0


def kv_prefix_ranks(client, prefix, size):
    """{rank: value string} for every ``<prefix><rank>`` key published in
    the coordination-service KV store — ONE ``key_value_dir_get`` (carried
    by every jaxlib 0.4+ client), falling back to per-rank
    ``key_value_try_get`` (which only newer clients have; the pinned
    0.4.37 does NOT — discovered in ISSUE 12, where the try_get-only scan
    made the dead-node check misreport every rank as dead).  The ONE
    implementation behind both :func:`barrier`'s arrival marks and the
    trainhealth heartbeat exchange; every failure degrades to
    absent-key."""
    out = {}
    try:
        pairs = client.key_value_dir_get(prefix)
    except Exception:
        pairs = None
    if pairs is not None:
        for k, v in pairs:
            try:
                out[int(str(k).rsplit("/", 1)[-1])] = str(v)
            except ValueError:
                pass
        return out
    for r in range(size):
        try:
            v = client.key_value_try_get(prefix + str(r))
        except Exception:
            v = None
        if v:
            out[r] = str(v)
    return out


_barrier_seq = 0


def barrier(name="mxnet_barrier", timeout_ms=None):
    """Block until every process arrives (reference ``KVStore::Barrier``,
    ``kvstore_dist.h:96``).

    ``timeout_ms`` defaults to env ``MXNET_DIST_BARRIER_TIMEOUT_MS`` (else
    120 s); an explicitly passed value always wins over the env, matching
    ``init()``'s precedence.  On timeout the coordination-service KV store
    is queried for per-rank arrival marks and a :class:`DeadNodeError`
    NAMING the non-arrived ranks is raised — the reference's dead-node
    check (``ps::Postoffice::GetDeadNodes`` at barrier setup,
    kvstore_dist.h:110-118) rebuilt on the TPU stack.  A lost rank
    therefore fails the job fast with a diagnostic instead of hanging it."""
    global _barrier_seq
    import jax

    if jax.process_count() == 1:
        return
    if timeout_ms is None:
        timeout_ms = int(os.environ.get("MXNET_DIST_BARRIER_TIMEOUT_MS", 120_000))
    client = getattr(jax._src.distributed.global_state, "client", None)
    if client is None:
        # jax moved the internals, or no coordination-service client (e.g.
        # proxy backends): fall back to an unbounded device sync
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
        return
    # barrier() is collective, so every process sees the same sequence
    # number; keys (unlike TSL barrier ids) are single-use, hence the suffix
    _barrier_seq += 1
    mark = "mxt_arrived/%s/%d" % (name, _barrier_seq)
    my_mark = "%s/%d" % (mark, jax.process_index())
    try:
        client.key_value_set(my_mark, "1")
    except Exception:
        import warnings

        warnings.warn("dist.barrier: failed to publish arrival mark %r — "
                      "on timeout OTHER ranks may misreport this one as "
                      "dead" % my_mark)
    try:
        client.wait_at_barrier("%s_%d" % (name, _barrier_seq), int(timeout_ms))
    except Exception as exc:
        # who never arrived?  One shared KV prefix scan over the
        # arrival marks (kv_prefix_ranks — the ISSUE 12 fix: the old
        # try_get-only loop misreported EVERY rank as dead on clients
        # without that method, e.g. the pinned jaxlib 0.4.37)
        arrived = kv_prefix_ranks(client, mark + "/", jax.process_count())
        missing = [r for r in range(jax.process_count())
                   if r not in arrived]
        if missing:
            raise DeadNodeError(name, missing, timeout_ms) from exc
        raise
    # passed: drop this rank's mark so coordinator KV state stays bounded
    # over long jobs (barriers can run every sync interval for days)
    try:
        client.key_value_delete(my_mark)
    except Exception:
        pass


def shutdown():
    global _initialized
    import jax

    if _initialized and jax.process_count() > 1:
        jax.distributed.shutdown()
    _initialized = False
