"""Multi-host bootstrap — replaces ``tools/launch.py`` + dmlc tracker.

The reference spawns scheduler/server/worker roles over ssh/mpi/yarn and wires
them through ps-lite (``src/kvstore/kvstore_dist.h:50-55``, SURVEY §3.5).  The
TPU-native design has no parameter servers: every host is a worker, and
``jax.distributed.initialize`` + DCN collectives replace the tracker and RPC.

Environment contract (mirrors the reference's DMLC_* env protocol):
  MXNET_COORDINATOR  — "host:port" of process 0 (≡ scheduler address)
  MXNET_NUM_WORKERS  — total process count (≡ DMLC_NUM_WORKER)
  MXNET_WORKER_RANK  — this process's rank   (≡ DMLC_RANK)
Standard TPU-pod env (Cloud TPU metadata) is auto-detected by JAX when these
are absent, so on real pods ``init()`` with no args is enough.
"""
from __future__ import annotations

import os

_initialized = False


def init(coordinator_address=None, num_processes=None, process_id=None, **kw):
    """Initialize multi-host JAX.  Idempotent; no-op in single-process runs
    unless coordinator env/args are present."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("MXNET_COORDINATOR")
    if num_processes is None and "MXNET_NUM_WORKERS" in os.environ:
        num_processes = int(os.environ["MXNET_NUM_WORKERS"])
    if process_id is None and "MXNET_WORKER_RANK" in os.environ:
        process_id = int(os.environ["MXNET_WORKER_RANK"])
    if coordinator_address is None and num_processes is None:
        # single-host; jax.distributed not needed
        _initialized = True
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kw,
    )
    _initialized = True


def rank():
    """This host's index (reference ``KVStore.rank``, ``kvstore_dist.h:106``)."""
    import jax

    return jax.process_index()


def size():
    """Number of hosts (reference ``KVStore.num_workers``)."""
    import jax

    return jax.process_count()


def is_coordinator():
    return rank() == 0


def barrier(name="mxnet_barrier", timeout_ms=120_000):
    """Block until every process arrives (reference ``KVStore::Barrier``,
    ``kvstore_dist.h:96``).  Uses the coordination-service barrier (bounded by
    ``timeout_ms``) when available; desync/timeout errors propagate — a
    missing host is a real failure, not something to paper over."""
    import jax

    if jax.process_count() == 1:
        return
    client = getattr(jax._src.distributed.global_state, "client", None)
    if client is None:
        # jax moved the internals, or no coordination-service client (e.g.
        # proxy backends): fall back to an unbounded device sync
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
        return
    client.wait_at_barrier(name, timeout_ms)


def shutdown():
    global _initialized
    import jax

    if _initialized and jax.process_count() > 1:
        jax.distributed.shutdown()
    _initialized = False
