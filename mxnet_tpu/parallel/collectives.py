"""Pytree collectives — the XLA replacement for the reference's Comm tree
(``src/kvstore/comm.h:43``: CommCPU host reduce, CommDevice GPU P2P reduce)
and the NCCL kvstore (``src/kvstore/kvstore_nccl.h``).

Inside ``shard_map``/``pjit`` these lower to ICI collectives; outside a mapped
context they fall back to identity (single-replica), mirroring how the
reference's ``local`` kvstore degenerates on one device.
"""
from __future__ import annotations

__all__ = ["allreduce", "pmean", "allgather", "reduce_scatter", "psum_scatter"]


def _tree_map(fn, tree):
    import jax

    return jax.tree_util.tree_map(fn, tree)


def allreduce(tree, axis_name="dp"):
    """Sum each leaf over ``axis_name``.  ≡ KVStore push+pull of every key
    (reference ``kvstore_dist.h:202,208``) collapsed into one fused collective."""
    import jax

    return _tree_map(lambda x: jax.lax.psum(x, axis_name), tree)


def pmean(tree, axis_name="dp"):
    """Mean over ``axis_name`` — the gradient-averaging step of dist_sync."""
    import jax

    return _tree_map(lambda x: jax.lax.pmean(x, axis_name), tree)


def allgather(tree, axis_name="dp", axis=0, tiled=True):
    """Gather shards along ``axis`` from every member of ``axis_name``."""
    import jax

    return _tree_map(
        lambda x: jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled), tree
    )


def reduce_scatter(tree, axis_name="dp", axis=0):
    """Sum then scatter along ``axis`` — the bandwidth-optimal half of an
    allreduce; use with ZeRO-style sharded optimizer states."""
    import jax

    return _tree_map(
        lambda x: jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True),
        tree,
    )


psum_scatter = reduce_scatter
