"""Pytree collectives — the XLA replacement for the reference's Comm tree
(``src/kvstore/comm.h:43``: CommCPU host reduce, CommDevice GPU P2P reduce)
and the NCCL kvstore (``src/kvstore/kvstore_nccl.h``).

Inside ``shard_map``/``pjit`` these lower to ICI collectives; outside a mapped
context they fall back to identity (single-replica), mirroring how the
reference's ``local`` kvstore degenerates on one device.
"""
from __future__ import annotations

__all__ = ["allreduce", "pmean", "allgather", "reduce_scatter",
           "psum_scatter", "note_derived"]


def _tree_map(fn, tree):
    import jax

    return jax.tree_util.tree_map(fn, tree)


def _note_bytes(op, tree):
    """Telemetry bytes-moved counter for one collective call.

    These collectives run inside jit, so this executes while TRACING: the
    counter measures declared bytes per compiled collective (one sample per
    trace), not per device execution — the per-step multiplier is the step
    count, which telemetry already tracks.  No-op when telemetry is off."""
    from .. import telemetry

    if not telemetry.enabled():
        return
    import jax

    n = sum(telemetry.array_nbytes(leaf)
            for leaf in jax.tree_util.tree_leaves(tree))
    telemetry.note_bytes("collective_bytes_total", n, op=op)


def note_derived(op, tree, mesh=None, axis="dp"):
    """Record telemetry bytes for a collective GSPMD *derives* from sharding
    annotations rather than an explicit ``lax`` call site — the sharded
    fused Module step (``module/fused_step.py``) declares its in-step grad
    psum / ZeRO reduce-scatter / param allgather here.  Declared once per
    stepper *build* (one sample per collective layout), a coarser grain
    than the explicit collectives above (one sample per trace): a reshape
    retrace re-specializes the same logical collectives, so it is not
    re-declared.

    With ``mesh`` given, the same bytes also land in
    ``collective_link_bytes_total{link, op}`` bucketed by the slowest link
    the collective's ``axis`` crosses: ``dcn`` when walking that mesh axis
    crosses a process boundary (pod-spanning dp — the payload rides the
    data-center network at least once per hop ring), else ``ici``.  The
    unlabeled ``collective_bytes_total{op}`` series is unchanged, so
    existing dashboards keep working."""
    _note_bytes(op, tree)
    if mesh is None:
        return
    from .. import telemetry

    if not telemetry.enabled():
        return
    import jax

    from .mesh import mesh_axis_spans_processes

    link = "dcn" if mesh_axis_spans_processes(mesh, axis) else "ici"
    n = sum(telemetry.array_nbytes(leaf)
            for leaf in jax.tree_util.tree_leaves(tree))
    telemetry.note_bytes("collective_link_bytes_total", n, link=link, op=op)


def allreduce(tree, axis_name="dp"):
    """Sum each leaf over ``axis_name``.  ≡ KVStore push+pull of every key
    (reference ``kvstore_dist.h:202,208``) collapsed into one fused collective."""
    import jax

    _note_bytes("allreduce", tree)
    return _tree_map(lambda x: jax.lax.psum(x, axis_name), tree)


def pmean(tree, axis_name="dp"):
    """Mean over ``axis_name`` — the gradient-averaging step of dist_sync."""
    import jax

    _note_bytes("pmean", tree)
    return _tree_map(lambda x: jax.lax.pmean(x, axis_name), tree)


def allgather(tree, axis_name="dp", axis=0, tiled=True):
    """Gather shards along ``axis`` from every member of ``axis_name``."""
    import jax

    _note_bytes("allgather", tree)
    return _tree_map(
        lambda x: jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled), tree
    )


def reduce_scatter(tree, axis_name="dp", axis=0):
    """Sum then scatter along ``axis`` — the bandwidth-optimal half of an
    allreduce; use with ZeRO-style sharded optimizer states."""
    import jax

    _note_bytes("reduce_scatter", tree)
    return _tree_map(
        lambda x: jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True),
        tree,
    )


psum_scatter = reduce_scatter
