"""Pipeline parallelism over a ``pp`` mesh axis — GPipe-style microbatching.

The reference's only "pipeline" story is manual per-layer device placement
(`group2ctx` → `nnvm::pass::PlaceDevice`, `src/executor/graph_executor.cc:407`,
with `_CrossDeviceCopy` hops and NO overlap: one device computes while the
others idle).  The TPU-native version is a real pipeline: each device owns
one stage's weights, M microbatches stream through, and at steady state all
stages compute concurrently while `lax.ppermute` moves activations over ICI
— the schedule the reference could not express.

Constraints (the standard SPMD pipeline contract): stages are uniform — one
``stage_fn`` applied S times with per-stage parameters whose leading axis is
sharded over ``pp`` — and every microbatch has the same shape.  Transformer /
MLP stacks fit this naturally.  The whole schedule is differentiable
(``ppermute`` has a transpose rule), so ``jax.grad`` through ``gpipe`` trains
the pipeline without any extra machinery.
"""
from __future__ import annotations

import functools

__all__ = ["gpipe", "stack_stage_params"]


def stack_stage_params(params_list):
    """Stack a list of S identical-structure pytrees along a new leading
    axis (stage axis) — shard that axis over ``pp`` with
    ``shard(x, P('pp', ...))`` so each device holds its own stage."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *params_list)


def gpipe(stage_fn, stacked_params, microbatches, *, mesh, axis="pp"):
    """Run ``S`` pipeline stages over ``M`` microbatches.

    Parameters
    ----------
    stage_fn : callable ``(stage_params, x) -> y`` with ``y.shape == x.shape``
        (uniform stages; compose shape changes into stage 0/embedding outside).
    stacked_params : pytree with leading dim ``S = mesh.shape[axis]``
        (stage-stacked, e.g. from :func:`stack_stage_params`); sharded or
        replicated — the shard_map slices each device's stage.
    microbatches : array ``(M, mb, ...)`` — the global batch split into M
        equal microbatches (replicated across ``pp``).
    mesh : the device mesh holding ``axis``.

    Returns ``(M, mb, ...)`` outputs after all S stages, replicated.

    Schedule: ``M + S - 1`` ticks; on tick ``t`` device ``d`` processes
    microbatch ``t - d`` (when valid), then activations ppermute one hop
    right.  Bubble fraction is ``(S-1)/(M+S-1)`` — pick ``M >= 4*S`` for
    >75% steady-state utilization.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .shard_map_compat import shard_map, pvary

    S = mesh.shape[axis]
    M = microbatches.shape[0]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != S:
            raise ValueError(
                "stacked_params leading dim %d != %d pipeline stages (mesh "
                "axis %r); one stage per device" % (leaf.shape[0], S, axis))

    p_specs = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)

    def per_device(p_stacked, xs):
        # p_stacked leaves: (1, ...) — this device's stage slice
        p = jax.tree_util.tree_map(lambda a: a[0], p_stacked)
        d = jax.lax.axis_index(axis)
        # pvary: the carries differ per stage — mark them axis-varying so
        # the fori_loop carry types line up under shard_map
        state = pvary(jnp.zeros_like(xs[0]), (axis,))
        outs = pvary(jnp.zeros_like(xs), (axis,))

        def tick(t, carry):
            state, outs = carry
            # stage 0 ingests microbatch t (clamped; masked out when t >= M)
            feed = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            x_in = jnp.where(d == 0, feed, state)
            y = stage_fn(p, x_in)
            # last stage banks microbatch t - (S-1) when in range
            widx = jnp.clip(t - (S - 1), 0, M - 1)
            bank = (d == S - 1) & (t >= S - 1)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(bank, y, jax.lax.dynamic_index_in_dim(
                    outs, widx, axis=0, keepdims=False)),
                widx, axis=0)
            # activations hop one stage right over ICI
            state = jax.lax.ppermute(
                y, axis, [(i, i + 1) for i in range(S - 1)])
            return state, outs

        state, outs = jax.lax.fori_loop(0, M + S - 1, tick, (state, outs))
        # replicate the last stage's bank to every device
        mask = (d == S - 1).astype(outs.dtype)
        return jax.lax.psum(outs * mask, axis)

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(p_specs, P()), out_specs=P())
    return fn(stacked_params, microbatches)
