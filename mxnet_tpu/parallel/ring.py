"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has NO long-context machinery (SURVEY §5.7: bucketing + memory
mirror only); this subsystem is the TPU-native upgrade that makes sequence
length a first-class sharded dimension.  Design: blockwise attention with
online softmax, K/V blocks rotated around the ``sp`` mesh axis with
``lax.ppermute`` so each step overlaps compute with ICI transfer
(Liu et al., Ring Attention; see PAPERS.md).

Use :func:`ring_attention` inside an existing ``shard_map``, or
:func:`ring_self_attention` as a standalone entry that builds the shard_map
over the current mesh.
"""
from __future__ import annotations

from functools import partial

__all__ = ["ring_attention", "ring_self_attention"]


def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None):
    """Blockwise attention with K/V ring rotation.  Call inside shard_map.

    q: [B, H, Sq, D] local query block; k, v: [B, H, Skv, D] local key/value
    blocks (sequence dimension sharded over ``axis_name``).  Returns the
    attention output for the local query block: [B, H, Sq, D].
    """
    import jax
    import jax.numpy as jnp

    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, H, Sq, D = q.shape
    Skv = k.shape[2]
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    q32 = (q * scale).astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    neg_inf = jnp.finfo(jnp.float32).min

    def accumulate(o, m, l, k_blk, v_blk, t):
        # block currently held arrived from device (my_idx - t) mod n
        src = (my_idx - t) % n
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_blk.astype(jnp.float32))
        if causal:
            qpos = my_idx * Sq + jnp.arange(Sq)
            kpos = src * Skv + jnp.arange(Skv)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, neg_inf)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        # guard fully-masked rows: exp(neg_inf - neg_inf) otherwise NaNs
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        if causal:
            p = jnp.where(jnp.isfinite(m_new), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        return o_new, m_new, l_new

    def step(carry, t):
        o, m, l, k_blk, v_blk = carry
        o, m, l = accumulate(o, m, l, k_blk, v_blk, t)
        # rotate K/V to the next device; overlaps with the next step's einsum
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_next, v_next), None

    o0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    m0 = jnp.full((B, H, Sq, 1), neg_inf, jnp.float32)
    l0 = jnp.zeros((B, H, Sq, 1), jnp.float32)
    # mark accumulators device-varying so the scan carry type matches
    # (shard_map VMA checking, jax ≥0.8)
    try:
        from .shard_map_compat import pvary
        o0, m0, l0 = (pvary(x, (axis_name,)) for x in (o0, m0, l0))
    except AttributeError:
        pass
    # scan n-1 rotate-steps, then consume the final block without rotating —
    # otherwise the last ppermute ships a full K+V block nobody reads
    if n > 1:
        (o, m, l, k_last, v_last), _ = jax.lax.scan(
            step, (o0, m0, l0, k, v), jnp.arange(n - 1)
        )
    else:
        o, m, l, k_last, v_last = o0, m0, l0, k, v
    o, m, l = accumulate(o, m, l, k_last, v_last, n - 1)
    out = o / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_self_attention(q, k, v, mesh=None, axis_name="sp", causal=False, scale=None):
    """Standalone ring attention: shards the sequence axis of [B, H, S, D]
    inputs over ``axis_name`` of ``mesh`` and runs :func:`ring_attention`."""
    from jax.sharding import PartitionSpec as P

    from .mesh import current_mesh
    from .shard_map_compat import shard_map

    mesh = mesh or current_mesh()
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis_name!r}: {mesh.axis_names}")
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        partial(ring_attention, axis_name=axis_name, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
