"""Expert parallelism — Mixture-of-Experts dispatch over an ``ep`` mesh axis.

Absent from the reference (SURVEY §2.2: EP/MoE "out of scope"); provided
here because expert parallelism is a first-class TPU distribution strategy:
each device owns one expert's FFN weights, tokens are routed top-1
(Switch-Transformer style) with fixed capacity, and two ``all_to_all``
collectives over ICI move token buffers to their experts and back — the
GShard dispatch/combine einsum formulation, which keeps everything dense,
static-shaped, and MXU-friendly (no gather/scatter of ragged groups).

Routing contract: ``n_experts == mesh.shape[axis]``; tokens beyond an
expert's capacity are dropped (output 0 for that token — standard Switch
behavior); the router is differentiable through the combine weights.
"""
from __future__ import annotations

__all__ = ["moe_ffn", "stack_expert_params"]


from .pipeline import stack_stage_params as stack_expert_params  # same op


def moe_ffn(x, gate_w, expert_params, expert_fn, *, mesh, axis="ep",
            capacity_factor=1.25):
    """Top-1 routed MoE layer over the ``axis`` mesh dimension.

    Parameters
    ----------
    x : (T, D) global tokens; the token axis is sharded over ``axis``
        (data parallel and expert parallel share the mesh axis, the usual
        MoE layout) — each device routes its ``T/E`` local tokens.
    gate_w : (D, E) router weights (replicated).
    expert_params : pytree with leading dim ``E = mesh.shape[axis]``
        (stacked experts; the shard_map slices one expert per device).
    expert_fn : ``(params_slice, tokens) -> tokens`` applied by each device
        to the tokens routed to its expert (it sees ``E*C`` tokens: ``C``
        slots from every source device).
    capacity_factor : buffer size multiplier; per-source capacity
        ``C = ceil(T/E / E * capacity_factor)``.

    Returns (T, D) outputs sharded like ``x``: gate-prob-weighted expert
    outputs (zero for capacity-dropped tokens).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .shard_map_compat import shard_map

    E = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(expert_params):
        if leaf.shape[0] != E:
            raise ValueError(
                "expert_params leading dim %d != %d experts (mesh axis %r)"
                % (leaf.shape[0], E, axis))
    T = x.shape[0]
    if T % E:
        raise ValueError("token count %d must divide over %d devices" % (T, E))
    if gate_w.shape[-1] != E:
        raise ValueError(
            "gate_w routes to %d experts but mesh axis %r has %d devices"
            % (gate_w.shape[-1], axis, E))
    C = max(1, int(-(-(T // E) * capacity_factor // E)))  # ceil
    p_specs = jax.tree_util.tree_map(lambda _: P(axis), expert_params)

    def per_device(x_loc, gw, p_stacked):
        p = jax.tree_util.tree_map(lambda a: a[0], p_stacked)
        logits = x_loc @ gw                           # (T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)           # (T,)
        gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
        # slot counting in int32: token dtype may be bf16, whose integers
        # stop being exact at 256 — silent slot collisions otherwise
        onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)    # (T, E)
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1          # (T, E)
        keep = (pos < C) & (onehot > 0)
        posc = jnp.clip(pos, 0, C - 1)
        # dispatch tensor (T, E, C): 1 where token t sits in slot c of e
        disp = (jax.nn.one_hot(posc, C, dtype=x_loc.dtype)
                * keep[..., None].astype(x_loc.dtype))
        buffers = jnp.einsum("tec,td->ecd", disp, x_loc)       # (E, C, D)
        # ship each expert's buffer to its device; receive (E, C, D) where
        # leading dim indexes SOURCE device after the exchange
        inbox = jax.lax.all_to_all(buffers, axis, split_axis=0,
                                   concat_axis=0, tiled=True)
        y = expert_fn(p, inbox.reshape(E * C, -1)).reshape(E, C, -1)
        outbox = jax.lax.all_to_all(y, axis, split_axis=0,
                                    concat_axis=0, tiled=True)
        combine = disp * gate[:, None, None]                   # (T, E, C)
        return jnp.einsum("tec,ecd->td", combine, outbox)

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(axis), P(), p_specs), out_specs=P(axis))
    return fn(x, gate_w, expert_params)
