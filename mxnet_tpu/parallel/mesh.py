"""Named device meshes and sharding helpers.

Replaces the reference's manual device placement (``group2ctx`` →
``nnvm::pass::PlaceDevice``, ``src/executor/graph_executor.cc:407``) and the
executor-group batch slicing (``python/mxnet/module/executor_group.py:143``)
with declarative ``jax.sharding`` over a named mesh.  Axis names follow the
scaling-book convention: ``dp`` (data), ``tp`` (tensor/model), ``pp``
(pipeline), ``sp`` (sequence/context), ``ep`` (expert).
"""
from __future__ import annotations

import threading

import numpy as np

AXIS_ORDER = ("pp", "dp", "sp", "ep", "tp")

_state = threading.local()


def local_mesh_devices(platform=None):
    """All addressable devices, in process-stable order."""
    import jax

    if platform:
        return jax.devices(platform)
    return jax.devices()


def make_mesh(axes=None, devices=None, **axis_sizes):
    """Create a named :class:`jax.sharding.Mesh`.

    ``make_mesh()`` → 1-D data-parallel mesh over every device.
    ``make_mesh(dp=2, tp=4)`` → 2×4 mesh with named axes.
    ``make_mesh({"dp": 2, "tp": 4})`` → same.
    Axis sizes of ``-1`` are inferred from the device count.
    Axes are laid out in :data:`AXIS_ORDER` so that the innermost (fastest
    varying, most bandwidth-hungry) axis ``tp`` lands on adjacent devices —
    collectives ride ICI, not DCN (SURVEY §5.8 north star).
    """
    from jax.sharding import Mesh

    if isinstance(axes, dict):
        axis_sizes = dict(axes, **axis_sizes)
        axes = None
    if axes is not None and not axis_sizes:
        # sequence of (name, size) pairs
        axis_sizes = dict(axes)

    devices = list(devices if devices is not None else local_mesh_devices())
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {"dp": n}

    # order axes canonically, keep user-given axes not in AXIS_ORDER at the end
    names = [a for a in AXIS_ORDER if a in axis_sizes]
    names += [a for a in axis_sizes if a not in AXIS_ORDER]

    sizes = [axis_sizes[a] for a in names]
    n_infer = sizes.count(-1)
    if n_infer > 1:
        raise ValueError("at most one mesh axis may be -1")
    if n_infer:
        known = int(np.prod([s for s in sizes if s != -1])) if len(sizes) > 1 else 1
        if n % known:
            raise ValueError(f"cannot infer axis size: {n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError(f"mesh of size {total} exceeds {n} available devices")
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def mesh_process_count(mesh):
    """Number of distinct JAX processes owning devices of ``mesh``.

    ``1`` for any single-host mesh; ``> 1`` means the mesh spans a pod —
    collectives over any axis crossing a process boundary ride DCN, batch
    arrays must be assembled from per-host shards
    (:func:`global_batch_array`), and a distributed KVStore's grad psum is
    subsumed by the in-step GSPMD collective
    (``KVStore.folds_into_fused_step``)."""
    if mesh is None:
        return 1
    return len({d.process_index for d in mesh.devices.flat})


def mesh_spans_processes(mesh):
    """True when ``mesh`` places devices from more than one process."""
    return mesh_process_count(mesh) > 1


def mesh_axis_local_size(mesh, axis="dp"):
    """Distinct coordinates along ``axis`` held by THIS process's devices.

    For a single-host mesh this equals ``mesh.shape[axis]``; over a pod it
    is the slice of the axis this host covers — the local-to-global batch
    scale is ``mesh.shape[axis] / mesh_axis_local_size(mesh, axis)``."""
    import jax

    if axis not in mesh.axis_names:
        return 1
    pos = mesh.axis_names.index(axis)
    pi = jax.process_index()
    coords = {idx[pos] for idx, dev in np.ndenumerate(mesh.devices)
              if dev.process_index == pi}
    return max(1, len(coords))


def mesh_batch_factor(mesh, axis="dp"):
    """Global-batch over local-batch scale for ``mesh`` along ``axis``.

    ``1`` on a single host; ``n_processes_spanned_by_axis`` over a pod —
    the factor ``Module`` applies to iterator-local leading dims to get the
    global shapes the jitted program binds."""
    if mesh is None or axis not in getattr(mesh, "axis_names", ()):
        return 1
    return mesh.shape[axis] // mesh_axis_local_size(mesh, axis)


def mesh_axis_spans_processes(mesh, axis="dp"):
    """True when walking ``axis`` (other coords fixed) crosses a process
    boundary — the collective over that axis rides DCN, not ICI."""
    if mesh is None or axis not in getattr(mesh, "axis_names", ()):
        return False
    pos = mesh.axis_names.index(axis)
    devs = np.moveaxis(mesh.devices, pos, 0)
    flat = devs.reshape(devs.shape[0], -1)
    for col in range(flat.shape[1]):
        if len({d.process_index for d in flat[:, col]}) > 1:
            return True
    return False


def global_batch_array(local, mesh, spec):
    """Assemble one globally-shaped, mesh-sharded ``jax.Array`` from THIS
    process's local batch shard — no host ever gathers another host's data.

    ``local`` is the rows this host's data pipeline produced (numpy or
    array-like); ``spec`` is the partition spec (first entry names the batch
    axis, canonically ``"dp"``).  The global shape scales the leading dim by
    :func:`mesh_batch_factor`; per-device buffers are cut from ``local`` in
    ascending global-offset order (devices sharing an identical leading
    slice — replicated trailing axes — receive the same chunk) and stitched
    with ``jax.make_array_from_single_device_arrays``.  With the default
    ``make_mesh`` layout, process ``r``'s rows land at global offset
    ``r * local_rows``, so a pod run feeding each rank the matching slice of
    one logical dataset is bit-identical to the single-process run on the
    full batch."""
    import jax

    spec = tuple(spec) if isinstance(spec, (list, tuple)) else (spec,)
    sh = named_sharding(mesh, *spec)
    arr = np.asarray(local)
    axis = spec[0] if spec and spec[0] else "dp"
    factor = mesh_batch_factor(mesh, axis)
    if factor == 1:
        return jax.device_put(arr, sh)
    global_shape = (arr.shape[0] * factor,) + tuple(arr.shape[1:])
    idx_map = sh.addressable_devices_indices_map(global_shape)
    by_start = {}
    for dev, idx in idx_map.items():
        lead = idx[0] if idx else slice(None)
        by_start.setdefault(lead.start or 0, []).append((dev, idx))
    starts = sorted(by_start)
    if arr.shape[0] % len(starts):
        raise ValueError(
            "local batch of %d rows not divisible over %d local shards"
            % (arr.shape[0], len(starts)))
    chunk = arr.shape[0] // len(starts)
    bufs = []
    for i, start in enumerate(starts):
        rows = arr[i * chunk:(i + 1) * chunk]
        for dev, idx in by_start[start]:
            piece = rows[(slice(None),) + tuple(idx[1:])] if len(idx) > 1 \
                else rows
            bufs.append(jax.device_put(piece, dev))
    return jax.make_array_from_single_device_arrays(global_shape, sh, bufs)


def host_local_rows(x):
    """This process's contiguous leading-axis block of a (possibly
    process-spanning) ``jax.Array``, as numpy — the metric/readback
    counterpart of :func:`global_batch_array`.  A fully-replicated array
    returns its full value; a dp-sharded one returns exactly the rows this
    host fed, so per-rank metrics line up with per-rank labels."""
    import numpy as np

    shards = getattr(x, "addressable_shards", None)
    if not shards:
        return np.asarray(x)
    by_start = {}
    for s in shards:
        lead = s.index[0] if s.index else slice(None)
        by_start.setdefault(lead.start or 0, s)
    parts = [np.asarray(by_start[k].data) for k in sorted(by_start)]
    return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)


def set_default_mesh(mesh):
    """Install ``mesh`` as the process default (returned by current_mesh())."""
    _state.default = mesh
    return mesh


def default_mesh():
    """The process-default mesh, creating a 1-D dp mesh on first use."""
    mesh = getattr(_state, "default", None)
    if mesh is None:
        mesh = set_default_mesh(make_mesh())
    return mesh


def current_mesh():
    """The innermost active ``with mesh:`` scope, else the process default."""
    import jax

    try:
        env_mesh = jax._src.mesh.thread_resources.env.physical_mesh  # active `with Mesh` scope
        if env_mesh is not None and not env_mesh.empty:
            return env_mesh
    except Exception:
        pass
    return default_mesh()


def named_sharding(mesh, *spec):
    """``NamedSharding(mesh, PartitionSpec(*spec))`` with None passthrough."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec))


def shard(x, spec, mesh=None):
    """Place ``x`` on ``mesh`` with partition ``spec`` (tuple of axis names/None).

    Works on NDArray, jax.Array, or numpy; returns the same kind it got.
    """
    import jax

    mesh = mesh or current_mesh()
    sh = named_sharding(mesh, *(spec if isinstance(spec, (list, tuple)) else (spec,)))
    from ..ndarray.ndarray import NDArray

    if isinstance(x, NDArray):
        return NDArray(jax.device_put(x._data, sh), ctx=x._ctx)
    return jax.device_put(x, sh)


def replicate(x, mesh=None):
    """Fully replicate ``x`` over the mesh."""
    return shard(x, (), mesh=mesh)


def shard_params(params, mesh=None, rules=None):
    """Shard a dict/pytree of parameters by name-matching rules.

    ``rules`` is a list of ``(substring, spec)`` pairs checked in order; the
    first match wins, default is full replication (pure data parallelism —
    the reference's only mode, SURVEY §2.2).  This is the declarative
    equivalent of KVStore key-sharding (``EncodeDefaultKey``,
    ``src/kvstore/kvstore_dist.h:522``).
    """
    import jax

    mesh = mesh or current_mesh()
    rules = rules or []

    def place(path, v):
        for substr, spec in rules:
            if substr in path:
                return shard(v, spec, mesh)
        return replicate(v, mesh)

    if isinstance(params, dict):
        return {k: place(k, v) for k, v in params.items()}
    flat, tree = jax.tree_util.tree_flatten_with_path(params)
    out = [place(jax.tree_util.keystr(path), v) for path, v in flat]
    return jax.tree_util.tree_unflatten(tree, out)


def zero_shard_spec(v, mesh, axis="dp"):
    """ZeRO/FSDP partition rule for one array: split the first axis that the
    ``axis`` mesh dimension divides, replicate otherwise (scalars, biases and
    BN vectors are noise next to weight matrices).  The single source of
    truth for optimizer-state sharding — used by
    ``gluon.functional.make_train_step(shard_optimizer_states=True)``, the
    Module fused step's ZeRO-1 mode (``module/fused_step.py``,
    ``MXNET_FUSED_ZERO``) and the ``__graft_entry__`` ZeRO dryrun phase.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    n = mesh.shape[axis]
    for ax in range(v.ndim):
        if v.shape[ax] % n == 0 and v.shape[ax] >= n:
            return NamedSharding(mesh, PartitionSpec(
                *([None] * ax + [axis] + [None] * (v.ndim - ax - 1))))
    return NamedSharding(mesh, PartitionSpec())


def place_committed(v, sharding):
    """Commit ``v`` to ``sharding`` unless it is already there — the
    idempotent device_put both :func:`zero1_place` and the fused stepper's
    per-step placement use (steady state reduces to one sharding ==
    check per array)."""
    import jax

    if getattr(v, "sharding", None) == sharding:
        return v
    return jax.device_put(v, sharding)


def zero1_shardings(tree, mesh, axis="dp"):
    """Pytree of :func:`zero_shard_spec` shardings matching ``tree`` — the
    ZeRO-1 partition layout for an optimizer-state (or parameter) pytree.

    Pin these as jit ``out_shardings`` and GSPMD derives the reduce-scatter
    of the gradients feeding the shard update and the allgather of whatever
    consumes the result, inside the same XLA module (no hand-written
    collective calls)."""
    import jax

    return jax.tree_util.tree_map(lambda v: zero_shard_spec(v, mesh, axis),
                                  tree)


def zero1_place(tree, mesh, axis="dp"):
    """Partition ``tree`` over ``axis`` ZeRO-1 style → (placed, shardings).

    Each leaf is ``device_put`` with its :func:`zero_shard_spec`; the
    returned shardings pytree is what callers pin as jit ``out_shardings``
    (donation then recycles the per-device shards every step).  Shared by
    the Module fused step's ZeRO-1 mode and the ``__graft_entry__`` ZeRO
    dryrun so both exercise the same partition logic."""
    import jax

    sh = zero1_shardings(tree, mesh, axis)
    placed = jax.tree_util.tree_map(place_committed, tree, sh)
    return placed, sh


def zero1_state_bytes(tree):
    """Per-device bytes actually held for a (possibly sharded) state pytree
    — the memory side of the ZeRO-1 ledger (docs/PERF_NOTES.md)."""
    import jax
    import numpy as np

    total = 0
    for v in jax.tree_util.tree_leaves(tree):
        shard_shape = v.sharding.shard_shape(v.shape) if hasattr(
            v, "sharding") else v.shape
        total += int(np.prod(shard_shape)) * v.dtype.itemsize
    return total
