"""shard_map import shim — jax.shard_map (≥0.8) vs jax.experimental.shard_map."""
from __future__ import annotations

try:
    from jax import shard_map  # jax ≥ 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore

__all__ = ["shard_map"]
