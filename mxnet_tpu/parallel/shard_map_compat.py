"""Version shims: jax.shard_map (≥0.8) vs jax.experimental.shard_map, and
pvary (deprecated in 0.9) vs lax.pcast(..., to='varying')."""
from __future__ import annotations

try:
    from jax import shard_map  # jax ≥ 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore


def pvary(x, axis_names):
    """Mark ``x`` as varying over the given manual axes (shard_map typing)."""
    import jax

    if hasattr(jax.lax, "pcast"):  # jax ≥ 0.9
        return jax.lax.pcast(x, tuple(axis_names), to="varying")
    if hasattr(jax.lax, "pvary"):  # the varying-types era before pcast
        return jax.lax.pvary(x, tuple(axis_names))  # pragma: no cover
    # jax ≤ 0.4.x: shard_map has no varying-type annotations — values are
    # implicitly device-varying inside the manual region, identity is the
    # correct (and only) marking
    return x


__all__ = ["shard_map", "pvary"]
