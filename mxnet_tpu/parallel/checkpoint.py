"""Sharded / async checkpointing over orbax — the TPU-native build of the
reference's checkpoint/resume subsystem (SURVEY §5.4).

Reference counterparts:
- `NDArray` save/load of name→array maps (`src/ndarray/ndarray.cc`,
  `MXNDArraySave/Load`) → `mxnet_tpu.nd.save/load` (host, single-file) for
  small/host-side state; THIS module for device-sharded state.
- `Module.save_checkpoint` / `callback.do_checkpoint` epoch rotation
  (`python/mxnet/module/module.py:165`, `python/mxnet/callback.py:55`) →
  :class:`CheckpointManager` (step-indexed, max-to-keep rotation).
- Recovery story "epoch checkpoints + relaunch" (`SURVEY §5.3`; ps-lite
  `is_recovery` restart flag) → :func:`restore` reshards a checkpoint onto
  whatever mesh the restarted job has, so a job can come back on a
  different topology — strictly stronger than the reference's
  same-topology relaunch.

Why orbax rather than the reference's single-file format: sharded
`jax.Array`s live distributed over chips/hosts; every host writes its own
shards concurrently (OCDBT), and `async_save` overlaps serialization with
the next training step — the reference's engine-async `NDArray::Save` had
the same motivation on one host.
"""
from __future__ import annotations

import os
import threading

__all__ = ["save", "async_save", "restore", "wait_all",
           "CheckpointManager"]

_PENDING = []
_LOCK = threading.Lock()


def _to_jax_tree(tree):
    from ..ndarray import NDArray

    import jax

    return jax.tree_util.tree_map(
        lambda v: v._data if isinstance(v, NDArray) else v, tree,
        is_leaf=lambda v: isinstance(v, NDArray))


def _abstract_like(like):
    """Target-layout tree: shapes/dtypes/shardings restored arrays must take."""
    import jax

    return jax.tree_util.tree_map(
        lambda v: jax.ShapeDtypeStruct(
            v.shape, v.dtype, sharding=getattr(v, "sharding", None)),
        _to_jax_tree(like))


def _verify_like_shapes(meta, like_abs):
    """Fail loudly when ``like`` asks for a different *global* shape than
    the checkpoint holds.  Orbax silently slices a larger saved array down
    to a smaller requested shape — an elastic relaunch against the wrong
    model would restore truncated garbage instead of raising.  Resharding
    changes layout, never global shape, so any shape disagreement is a
    real mismatch.  ``meta`` may be None (metadata unavailable) — then the
    check is skipped and orbax's own structure errors still apply."""
    import jax

    if meta is None:
        return
    mismatched = []

    def _chk(path, m, l):
        ms = getattr(m, "shape", None)
        ls = getattr(l, "shape", None)
        if ms is not None and ls is not None and tuple(ms) != tuple(ls):
            mismatched.append("%s: saved %s, requested %s"
                              % (jax.tree_util.keystr(path),
                                 tuple(ms), tuple(ls)))

    try:
        jax.tree_util.tree_map_with_path(_chk, meta, like_abs)
    except (ValueError, TypeError):
        # tree-structure disagreement: let orbax raise its own (clearer)
        # structure error from the restore itself
        return
    if mismatched:
        raise ValueError(
            "checkpoint/like global-shape mismatch (refusing a silently "
            "truncated restore): " + "; ".join(mismatched))


def _checkpointer(use_async=False):
    import orbax.checkpoint as ocp

    return ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler()) \
        if use_async else ocp.StandardCheckpointer()


def save(path, tree, force=True):
    """Synchronously save a pytree of (possibly sharded) arrays.

    name→NDArray dicts work like ``nd.save``; sharded ``jax.Array`` trees
    are written with each host storing its own shards.
    """
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(path), _to_jax_tree(tree), force=force)
    ckptr.close()


def async_save(path, tree, force=True):
    """Start a checkpoint write in the background; training continues while
    shards serialize (the device→host copy happens before return, so the
    next step may freely donate/overwrite the arrays).

    One long-lived AsyncCheckpointer is shared by all calls (repeated saves
    reuse its worker instead of leaking one thread pool per call; a second
    save first waits for the previous commit, orbax's usual pipelining).
    Concurrent ``async_save`` callers serialize on the module lock for the
    whole enqueue — intentional: orbax's ``save`` blocks until the previous
    commit finishes anyway, and holding the lock keeps a concurrent
    :func:`wait_all` from closing the shared checkpointer mid-save.
    Returns an object with ``wait_until_finished()``; :func:`wait_all`
    drains every pending save (call before exit — mirrors the reference's
    ``Engine::WaitForAll`` before shutdown).
    """
    with _LOCK:
        if not _PENDING:
            import atexit

            _PENDING.append(_checkpointer(use_async=True))
            atexit.register(wait_all)
        ckptr = _PENDING[0]
        # enqueue under the lock: a concurrent wait_all must not close this
        # checkpointer between lookup and save
        ckptr.save(os.path.abspath(path), _to_jax_tree(tree), force=force)
    return ckptr


def wait_all():
    """Block until every async checkpoint write has committed."""
    with _LOCK:
        pending, _PENDING[:] = _PENDING[:], []
    for c in pending:
        c.wait_until_finished()
        c.close()


def restore(path, like=None, mesh=None, rules=None):
    """Restore a checkpoint, resharding onto the current topology.

    - ``like``: a pytree of arrays (or ShapeDtypeStructs) giving target
      shapes/dtypes/shardings — restored arrays match its layout.
    - ``mesh`` + ``rules``: alternatively, place restored arrays by the
      name-matching spec rules of :func:`mxnet_tpu.parallel.shard_params`.
    - neither: arrays come back with the layout they were saved in
      (requires the same device topology, like the reference's relaunch).
    """
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = _checkpointer()
    try:
        if like is not None:
            like_abs = _abstract_like(like)
            try:
                meta = ckptr.metadata(path)
            except Exception:
                meta = None
            _verify_like_shapes(meta, like_abs)
            return ckptr.restore(path, like_abs)
        out = ckptr.restore(path)
        if mesh is not None:
            from .mesh import shard_params

            out = shard_params(out, mesh=mesh, rules=rules)
        return out
    finally:
        ckptr.close()


class CheckpointManager:
    """Step-indexed rotating checkpoints (reference
    ``callback.do_checkpoint`` + ``Module.save_checkpoint`` kept N epochs;
    here orbax's manager adds atomicity and async commit).

    >>> mgr = CheckpointManager(dir, max_to_keep=3)
    >>> mgr.save(step, state)            # async; rotates old steps out
    >>> state = mgr.restore(like=state)  # latest, resharded onto `like`
    """

    def __init__(self, directory, max_to_keep=5, save_interval_steps=1):
        import orbax.checkpoint as ocp

        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps))

    def save(self, step, tree, force=False):
        import orbax.checkpoint as ocp

        return self._mgr.save(
            step, args=ocp.args.StandardSave(_to_jax_tree(tree)),
            force=force)

    def restore(self, step=None, like=None):
        import jax
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoints in %s" % self._mgr.directory)
        if like is not None:
            like_abs = _abstract_like(like)
            try:
                meta = self._mgr.item_metadata(step)
            except Exception:
                meta = None
            _verify_like_shapes(meta, like_abs)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(like_abs))
        return self._mgr.restore(step)

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
