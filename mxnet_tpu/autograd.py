"""Imperative autograd — record/replay tape over pure registry ops.

TPU-native re-design of reference ``src/imperative/imperative.cc`` (RecordOp
tape + nnvm Gradient pass) and ``python/mxnet/autograd.py``.  Eager op calls
made inside a ``record()`` scope append (pure_fn, inputs, attrs, outputs)
entries to a tape; ``backward()`` replays the tape as a pure function of the
marked variables and differentiates it with ``jax.vjp``.  Replay recomputes
forward activations — rematerialization, the TPU-friendly trade (HBM is the
bottleneck; reference's MXNET_BACKWARD_DO_MIRROR made the same trade).
"""
from __future__ import annotations

import threading

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "mark_variables",
    "backward",
    "grad",
    "set_recording",
    "set_training",
    "Function",
]

_STATE = threading.local()


def _st():
    if not getattr(_STATE, "init", False):
        _STATE.recording = False
        _STATE.training = False
        _STATE.tape = []
        _STATE.marked = []
        _STATE.init = True
    return _STATE


class _TapeEntry:
    __slots__ = ("fn", "inputs", "input_vals", "attrs", "outputs")

    def __init__(self, fn, inputs, input_vals, attrs, outputs):
        self.fn = fn
        self.inputs = inputs  # list of NDArray (strong refs keep graph alive)
        self.input_vals = input_vals  # jax arrays at call time (pre-mutation snapshot)
        self.attrs = attrs
        self.outputs = outputs  # list of NDArray


def _record_op(fn, inputs, input_vals, attrs, outputs):
    """Called by the nd frontend after executing an op while recording
    (the Imperative::RecordOp hook, reference imperative.cc:183)."""
    _st().tape.append(_TapeEntry(fn, inputs, input_vals, attrs, outputs))


def _mark_variable(arr):
    st = _st()
    if all(m() is not arr for m in st.marked if m() is not None):
        import weakref

        st.marked.append(weakref.ref(arr))


def mark_variables(variables, gradients, grad_reqs="write"):
    """Reference autograd.py:216 — associate grad buffers with variables."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v.grad = g
        v._grad_req = req
        _mark_variable(v)


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    prev = _st().recording
    _st().recording = bool(is_record)
    return prev


def set_training(train):
    prev = _st().training
    _st().training = bool(train)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train):
        self._enter_record = is_record
        self._enter_train = train
        self._prev_r = None
        self._prev_t = None

    def __enter__(self):
        st = _st()
        if self._enter_record is not None:
            self._prev_r = st.recording
            if self._enter_record and not st.recording:
                st.tape = []  # fresh graph per recording session
            st.recording = self._enter_record
        if self._enter_train is not None:
            self._prev_t = st.training
            st.training = self._enter_train
        return self

    def __exit__(self, *a):
        st = _st()
        if self._prev_r is not None:
            st.recording = self._prev_r
        if self._prev_t is not None:
            st.training = self._prev_t


def record(train_mode=True):
    """``with autograd.record():`` — reference autograd.py:122."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    """``with autograd.pause():`` — reference autograd.py:146."""
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def _collect_live_marked():
    st = _st()
    out = []
    for ref in st.marked:
        v = ref()
        if v is not None and v._grad_req != "null":
            out.append(v)
    st.marked = [r for r in st.marked if r() is not None]
    return out


def _replay(tape, heads, var_list):
    """Build pure fn: marked var values -> head values, by tape replay.

    A differentiation variable that is ALSO a tape-produced intermediate
    keeps its traced binding — its producer entry must not clobber it, or
    gradients w.r.t. it silently vanish.  This gives leaf semantics (its
    own upstream history is cut), matching the reference where attaching a
    gradient to an intermediate detaches it (``python/mxnet/ndarray/
    ndarray.py attach_grad`` → ``self.detach()``) — the WGAN-GP
    interpolated-x̂ pattern.
    """
    var_ids = {id(v) for v in var_list}

    def f(var_vals):
        env = {id(v): val for v, val in zip(var_list, var_vals)}
        for entry in tape:
            args = []
            for nd_in, snap in zip(entry.inputs, entry.input_vals):
                args.append(env.get(id(nd_in), snap))
            out = entry.fn(*args, **entry.attrs)
            outs = out if isinstance(out, tuple) else (out,)
            for nd_out, val in zip(entry.outputs, outs):
                if id(nd_out) not in var_ids:
                    env[id(nd_out)] = val
        return [env.get(id(h), h._data) for h in heads]

    return f


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. marked variables (reference
    Imperative::Backward, imperative.cc:270) and += / = them into ``.grad``."""
    import jax
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray, _wrap

    st = _st()
    tape = st.tape
    var_list = _collect_live_marked()
    if not var_list:
        raise ValueError("There are no variables attached with gradients (attach_grad).")
    # only variables that PARTICIPATE in this graph get gradients written;
    # stale marked vars from earlier graphs keep their buffers untouched
    # (reference: only nodes in the backward graph receive kWriteTo)
    used = {id(i) for e in tape for i in e.inputs if i is not None}
    used.update(id(h) for h in heads)
    var_list = [v for v in var_list if id(v) in used]
    if not var_list:
        raise ValueError("None of the attached variables participate in the recorded graph.")
    f = _replay(tape, heads, var_list)
    var_vals = [v._data for v in var_list]
    outs, vjp_fn = jax.vjp(f, var_vals)
    if head_grads is None:
        cts = [jnp.ones_like(o) for o in outs]
    else:
        cts = [
            (g._data if isinstance(g, NDArray) else jnp.asarray(g)) if g is not None else jnp.ones_like(o)
            for o, g in zip(outs, head_grads)
        ]
    (grads,) = vjp_fn(cts)
    for v, g in zip(var_list, grads):
        if v._grad_req == "add" and v.grad is not None:
            v.grad._rebind(v.grad._data + g)
        elif v.grad is not None:
            # write INTO the marked buffer (reference kWriteTo): callers
            # holding the gradient array (mark_variables) see the update
            v.grad._rebind(g)
        else:
            v.grad = _wrap(g)
    if not retain_graph:
        st.tape = []


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False, train_mode=True):
    """Functional-style grad (reference autograd.py:270).

    ``create_graph=True`` (higher-order, reference parity): the returned
    gradients are themselves recorded on the tape — the gradient computation
    is a pure function of ``variables`` (a ``jax.vjp`` over the replayed
    prefix), so it becomes one tape entry whose replay jax can differentiate
    again (vjp-of-vjp).  A later ``backward()``/``grad()`` over anything
    computed from these gradients yields true second-order derivatives
    (the WGAN-GP gradient-penalty pattern).  As in the reference,
    ``retain_graph`` defaults to ``create_graph``.
    """
    import jax
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray, _wrap

    heads = heads if isinstance(heads, (list, tuple)) else [heads]
    variables = variables if isinstance(variables, (list, tuple)) else [variables]
    if retain_graph is None:
        retain_graph = create_graph
    st = _st()
    prefix = list(st.tape)  # the graph that produced ``heads``
    if head_grads is None:
        hg_list = None
        hgs = None
    else:
        hg_list = head_grads if isinstance(head_grads, (list, tuple)) else [head_grads]
        hgs = [g._data if isinstance(g, NDArray) else jnp.asarray(g) for g in hg_list]

    nvar = len(variables)
    if create_graph:
        # every other tape input (network parameters) and any NDArray
        # head_grad must be a traced input of the recorded grad op —
        # otherwise the outer backward sees them as constants and
        # second-order grads w.r.t. them (the WGAN-GP case) silently vanish
        # ...but tape-produced intermediates are NOT inputs: their traced
        # binding is overwritten by the producer entry during replay, so
        # including them only pins activations and adds dead cotangents
        produced = {id(o) for e in prefix for o in e.outputs}
        seen = {id(v) for v in variables}
        extra = []
        for e in prefix:
            for nd_in in e.inputs:
                if (nd_in is not None and id(nd_in) not in seen
                        and id(nd_in) not in produced):
                    seen.add(id(nd_in))
                    extra.append(nd_in)
        hg_nd = [] if hg_list is None else [
            g for g in hg_list if isinstance(g, NDArray)]
        all_nd = list(variables) + extra
        n_all = len(all_nd)

        def grad_fn(*vals):
            f = _replay(prefix, heads, all_nd)
            outs, vjp_fn = jax.vjp(f, list(vals[:n_all]))
            if hgs is None:
                cts = [jnp.ones_like(o) for o in outs]
            else:
                hg_vals = iter(vals[n_all:])
                cts = [next(hg_vals) if isinstance(g, NDArray) else c
                       for g, c in zip(hg_list, hgs)]
            (gs,) = vjp_fn(cts)
            return tuple(gs[:nvar])

        in_nd = all_nd + hg_nd
        in_vals = [v._data for v in in_nd]
        gvals = grad_fn(*in_vals)
        out_nd = [_wrap(g) for g in gvals]
        _record_op(grad_fn, in_nd, in_vals, {}, out_nd)
    else:
        # first-order: differentiate w.r.t. the requested variables only
        f = _replay(prefix, heads, variables)
        outs, vjp_fn = jax.vjp(f, [v._data for v in variables])
        cts = [jnp.ones_like(o) for o in outs] if hgs is None else list(hgs)
        (gs,) = vjp_fn(cts)
        out_nd = [_wrap(g) for g in gs]
    # create_graph keeps the WHOLE graph even under an explicit
    # retain_graph=False: later losses may mix the returned gradients with
    # pre-grad intermediates (e.g. ``(y·g).sum()``), and replaying those
    # from constant snapshots would train on silently wrong gradients
    if not (retain_graph or create_graph):
        st.tape = []
    return out_nd


def get_symbol(x):
    raise NotImplementedError("autograd.get_symbol is not supported; use symbol API directly")


class Function:
    """Custom differentiable function (reference autograd.py:363 Function).

    Subclass and implement ``forward``/``backward`` on NDArrays.  Internally
    wrapped as a jax.custom_vjp over the pure payloads.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        import jax

        from .ndarray.ndarray import NDArray, _wrap

        self_ref = self

        @jax.custom_vjp
        def _fn(*jargs):
            return _run_fwd(*jargs)

        def _run_fwd(*jargs):
            nd_in = [_wrap(a) for a in jargs]
            with pause():
                out = self_ref.forward(*nd_in)
            if isinstance(out, (list, tuple)):
                return tuple(o._data for o in out)
            return out._data

        def _fwd(*jargs):
            return _run_fwd(*jargs), jargs

        def _bwd(res, g):
            nd_g = [_wrap(x) for x in (g if isinstance(g, tuple) else (g,))]
            with pause():
                igrads = self_ref.backward(*nd_g)
            if not isinstance(igrads, (list, tuple)):
                igrads = (igrads,)
            return tuple(x._data for x in igrads)

        _fn.defvjp(_fwd, _bwd)

        from .ndarray import _invoke_raw

        return _invoke_raw(_fn, list(inputs), {})
