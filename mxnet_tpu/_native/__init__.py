"""ctypes loader for the native data-plane library (``src/`` in this repo).

The reference framework's data plane is C++ (``src/io/`` +
``3rdparty/dmlc-core`` recordio) reached through the C API
(``src/c_api/c_api.cc`` MXRecordIO*/MXDataIter*).  Here the native library is
``libmxtpu.so``, built lazily from ``src/`` with ``make`` on first use and
loaded over ctypes.  All callers must degrade gracefully to pure-Python
paths when the toolchain is unavailable (``lib() is None``).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_TRIED = False
_LOCK = threading.Lock()

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "libmxtpu.so")
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "src")


def _declare(lib):
    u64, i32, fp = ctypes.c_uint64, ctypes.c_int, ctypes.POINTER(ctypes.c_float)
    voidp, charp = ctypes.c_void_p, ctypes.c_char_p
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int)
    sigs = {
        "MXTRecordIOWriterCreate": (voidp, [charp]),
        "MXTRecordIOWriterWrite": (u64, [voidp, charp, u64]),
        "MXTRecordIOWriterTell": (u64, [voidp]),
        "MXTRecordIOWriterFree": (None, [voidp]),
        "MXTRecordIOReaderCreate": (voidp, [charp]),
        "MXTRecordIOReaderNext": (
            i32,
            [voidp, ctypes.POINTER(charp), ctypes.POINTER(u64)],
        ),
        "MXTRecordIOReaderSeek": (None, [voidp, u64]),
        "MXTRecordIOReaderTell": (u64, [voidp]),
        "MXTRecordIOReaderFree": (None, [voidp]),
        "MXTDecodeJPEG": (i32, [u8p, u64, u8p, u64, i32p, i32p, i32p]),
        "MXTResizeBilinear": (i32, [u8p, i32, i32, i32, u8p, i32, i32]),
        "MXTImageRecordLoaderCreate": (
            voidp,
            [charp, i32, i32, i32, i32, i32, i32, i32, i32, i32, u64, fp, fp],
        ),
        "MXTImageRecordLoaderSize": (u64, [voidp]),
        "MXTImageRecordLoaderNext": (i32, [voidp, fp, fp]),
        "MXTImageRecordLoaderReset": (None, [voidp]),
        "MXTImageRecordLoaderFree": (None, [voidp]),
    }
    for name, (restype, argtypes) in sigs.items():
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = argtypes
    return lib


def _build():
    if not os.path.isdir(_SRC_DIR):
        return False
    try:
        subprocess.run(
            ["make", "-s", "OUT=" + _SO_PATH],
            cwd=_SRC_DIR,
            check=True,
            capture_output=True,
            timeout=300,
        )
        return os.path.exists(_SO_PATH)
    except Exception:
        return False


def lib():
    """Returns the loaded native library, or None if unavailable."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("MXNET_TPU_DISABLE_NATIVE", "0") == "1":
            return None
        if not os.path.exists(_SO_PATH):
            src_newer = False
        else:
            try:
                so_mtime = os.path.getmtime(_SO_PATH)
                src_newer = any(
                    os.path.getmtime(os.path.join(root, f)) > so_mtime
                    for root, _, files in os.walk(_SRC_DIR)
                    for f in files
                    if f.endswith((".cc", ".h"))
                )
            except OSError:
                src_newer = True
        if (not os.path.exists(_SO_PATH)) or src_newer:
            if not _build():
                return None
        try:
            _LIB = _declare(ctypes.CDLL(_SO_PATH))
        except Exception:
            # stale .so missing a symbol, load failure, ... -> degrade to the
            # pure-Python paths rather than erroring the caller
            _LIB = None
        return _LIB
