"""Precision-flow analyzer + cast-plan contract (ISSUE 11 tentpole).

ROADMAP item 3 (the bf16/int8 inference-twin compilation tier) needs a way
to decide *statically* which nodes of a plan may drop precision and which
must keep fp32 accumulation — before any cast pass exists to get it wrong.
This module is that decision procedure, in the Relay/TVM "analyze before
you rewrite" spirit (PAPERS.md 1810.00952): an abstract interpretation
over the execution-plan IR that

1. propagates a **dtype lattice** through every node (via the shared
   ``_abstract_walk`` — the same ``jax.eval_shape`` walk, same
   ``node_call_attrs``, that ``Executor._graph_fn`` lowering follows) and
   flags silent downcasts, mixed-dtype binop promotions, f64 creep with
   the ORIGINATING node named, and low-precision accumulation;
2. runs an **interval analysis** seeded from known producer ranges
   (sigmoid/softmax outputs in [0, 1], BN-normalized activations, tanh in
   [-1, 1], baked constants' actual min/max) so exp/log-family ops can be
   judged by the range that actually reaches them, not pessimistically;
3. classifies every op against the numeric-sensitivity registry
   (``graph_passes.ir.op_sensitivity`` — colocated with ``node_call_attrs``
   so evaluation semantics and sensitivity classes live in one file) and
   combines (1)-(3) into a per-node verdict::

       bf16_safe    the node may compute entirely in bf16;
       fp32_accum   bf16 inputs are fine, the accumulator must stay fp32
                    (reductions, matmul/conv contractions, norm stats);
       fp32_only    keep the node in fp32 end to end (exp/log family
                    reached by an unbounded or unsafe range, cancellation
                    chains fed ranges we cannot bound).

The verdicts ship as a :class:`CastPlan` — the fingerprinted artifact the
future bf16-cast pass consumes (``Executor.precision_plan`` /
``Predictor.precision_plan``).  Its fingerprint covers the plan rows plus
``SENSITIVITY_VERSION`` and :data:`NUMERICS_VERSION`, and the
version-only :func:`contract_fingerprint` is folded into the AOT-cache
environment fingerprint (``compile_cache._env_fingerprint``) the same way
``graph_passes.pipeline_fingerprint()`` is — a registry reclassification
can never restore an executable compiled under the old numerics contract.

Everything here is static: ``jax.eval_shape`` only — no compile, no
device work.  Like every analyzer, a failure degrades to one INFO through
the manager, and a context without bound avals reports ``analyzer-skipped``
instead of silently looking clean.
"""
from __future__ import annotations

import hashlib
import json
import math

from ..graph_passes.ir import (CANCELLATION, EXP_RANGE, NEUTRAL, REDUCE,
                               SENSITIVITY_VERSION, node_attr,
                               op_sensitivity)
from . import register_analyzer
from .diagnostics import Diagnostic, WARNING

__all__ = ["numerics", "precision_plan", "CastPlan", "NUMERICS_VERSION",
           "contract_fingerprint", "param_verdict_classes",
           "BF16_SAFE", "FP32_ACCUM", "FP32_ONLY"]

# Bump on any change to verdict policy, diagnostics, or interval transfer
# functions — enters every CastPlan fingerprint and (via
# contract_fingerprint) the AOT-cache environment fingerprint.
NUMERICS_VERSION = 1

BF16_SAFE = "bf16_safe"
FP32_ACCUM = "fp32_accum"
FP32_ONLY = "fp32_only"

_INF = float("inf")
UNKNOWN = (-_INF, _INF)
_UNIT = (0.0, 1.0)
_SYM1 = (-1.0, 1.0)
# "BN-normalized activations" producer range: post-norm values are O(1);
# eight sigmas is generous enough to stay sound for any sane gamma/beta
# while still bounding downstream exp/log ops away from fp32_only
_NORMED = (-8.0, 8.0)

# |x| bound inside which exp-family ops tolerate bf16 input quantization:
# the relative output error of exp under input rounding is ~|x| * 2^-8,
# ~4% at x=10 — acceptable for inference twins; past it, fp32_only
_EXP_SAFE = 10.0
# log amplifies input error by 1/x near zero: below 2^-8 a one-ulp bf16
# input wiggle moves the output by more than bf16 can even represent
_LOG_SAFE_LO = 2.0 ** -8

_LOG_LIKE = frozenset({"log", "log1p", "log2", "log10", "gammaln", "gamma",
                       "_linalg_sumlogdiag"})
# shift-invariant exp family: softmax subtracts the row max internally, so
# the hazard is the input SPREAD, not its magnitude
_SHIFT_INVARIANT = frozenset({"softmax", "log_softmax", "softmin",
                              "SoftmaxActivation", "SoftmaxOutput"})
# two-input power (x**y = exp(y*ln x)): the output range depends on the
# JOINT base/exponent ranges (base near 0 with a negative exponent blows
# up inside intervals that look tame separately), so no per-input band
# test certifies it — never bf16_safe statically
_JOINT_POWER = frozenset({"_power", "broadcast_power"})

# float dtype widths by name — numpy calls bfloat16 kind "V", so
# issubdtype is useless here; unlisted names fall back to kind "f"
_FLOAT_BITS = {"float64": 64, "float32": 32, "float16": 16, "bfloat16": 16,
               "float8_e4m3fn": 8, "float8_e5m2": 8, "float8_e4m3": 8,
               "float8_e5m2fnuz": 8, "float8_e4m3fnuz": 8}

# REDUCE/CANCELLATION ops whose accumulation XLA performs in fp32 on the
# MXU regardless of input dtype (dot/conv contractions) — their verdict is
# still fp32_accum (the contract the cast pass must preserve), but a bf16
# input is NOT diagnosed as low-precision-accum: the hardware already
# accumulates wide.  jnp.sum/mean/var-style reductions accumulate in the
# input dtype and DO get the diagnostic.
_MXU_ACCUM = frozenset({"dot", "batch_dot", "FullyConnected", "Convolution",
                        "Deconvolution", "Correlation", "_linalg_gemm",
                        "_linalg_gemm2", "_linalg_syrk", "khatri_rao"})

# CANCELLATION-class norm ops deliberately mix precisions (fp32 moving
# stats against bf16 activations is the documented deployment norm, and
# e.g. LayerNorm upcasts to f32 internally) — exempt from the mixed-dtype
# and silent-downcast DIAGNOSTICS; their fp32_accum verdict still stands.
# "_fp32_island" (the ISSUE 15 bf16 tier's reduction wrapper) manages
# precision BY CONSTRUCTION — upcast in, fp32 accumulate, re-narrow out —
# and "_precision_cast" is the tier's explicit region-boundary convert.
# Neither exemption can change a diagnostic on a plan that contains no
# tier-synthesized node, so NUMERICS_VERSION stays put: tier-off contracts
# (and their cached executables) are untouched.
_PRECISION_MANAGED = frozenset({"BatchNorm", "LayerNorm", "InstanceNorm",
                                "_bn_affine", "LRN", "L2Normalization",
                                "_fp32_island"})
_EXPLICIT_CASTS = frozenset({"cast", "Cast", "amp_cast", "amp_multicast",
                             "_precision_cast"})


def _float_bits(dtype):
    """Bit width of a float dtype, or None for non-floats."""
    name = getattr(dtype, "name", None) or str(dtype)
    bits = _FLOAT_BITS.get(name)
    if bits is None and getattr(dtype, "kind", "") == "f":
        bits = dtype.itemsize * 8
    return bits


def _is_lowp(dtype):
    bits = _float_bits(dtype)
    return bits is not None and bits <= 16


# -- interval transfer functions ---------------------------------------------

def _widest(ivals):
    if not ivals:
        return UNKNOWN
    return (min(lo for lo, _ in ivals), max(hi for _, hi in ivals))


def _first(ivals):
    return ivals[0] if ivals else UNKNOWN


def _mul_iv(a, b):
    prods = []
    for x in a:
        for y in b:
            # inf * 0 is nan; the sound interval endpoint for it is 0
            prods.append(0.0 if (x == 0.0 or y == 0.0) else x * y)
    return (min(prods), max(prods))


def _passthrough(node, iv):
    return _first(iv)


def _relu_iv(node, iv):
    lo, hi = _first(iv)
    return (max(lo, 0.0), max(hi, 0.0))


def _activation_iv(node, iv):
    act = node_attr(node, "act_type")
    if act == "sigmoid":
        return _UNIT
    if act in ("tanh", "softsign"):
        return _SYM1
    if act == "relu":
        return _relu_iv(node, iv)
    if act == "softrelu":
        lo, hi = _first(iv)
        return (0.0, _INF if not math.isfinite(hi) else math.log1p(
            math.exp(min(hi, 700.0))))
    return UNKNOWN


def _clip_iv(node, iv):
    lo, hi = _first(iv)
    a_min = node_attr(node, "a_min")
    a_max = node_attr(node, "a_max")
    if a_min is not None:
        lo = max(lo, float(a_min))
        hi = max(hi, float(a_min))
    if a_max is not None:
        lo = min(lo, float(a_max))
        hi = min(hi, float(a_max))
    return (lo, hi)


def _exp_iv(node, iv):
    lo, hi = _first(iv)
    return (math.exp(min(lo, 700.0)) if math.isfinite(lo) else 0.0,
            math.exp(min(hi, 700.0)) if math.isfinite(hi) else _INF)


def _log_iv(node, iv):
    lo, hi = _first(iv)
    return (math.log(lo) if lo > 0 else -_INF,
            (math.log(hi) if hi > 0 else -_INF) if math.isfinite(hi)
            else _INF)


def _square_iv(node, iv):
    lo, hi = _first(iv)
    m = max(abs(lo), abs(hi))
    return (0.0 if lo <= 0.0 <= hi else min(lo * lo, hi * hi),
            m * m if math.isfinite(m) else _INF)


def _sqrt_iv(node, iv):
    lo, hi = _first(iv)
    return (math.sqrt(max(lo, 0.0)) if math.isfinite(lo) else 0.0,
            math.sqrt(max(hi, 0.0)) if math.isfinite(hi) else _INF)


def _add_iv(node, iv):
    (a, b), (c, d) = (iv + [UNKNOWN, UNKNOWN])[:2]
    return (a + c, b + d)


def _sub_iv(node, iv):
    (a, b), (c, d) = (iv + [UNKNOWN, UNKNOWN])[:2]
    return (a - d, b - c)


def _binmul_iv(node, iv):
    (a, b), (c, d) = (iv + [UNKNOWN, UNKNOWN])[:2]
    return _mul_iv((a, b), (c, d))


def _maximum_iv(node, iv):
    (a, b), (c, d) = (iv + [UNKNOWN, UNKNOWN])[:2]
    return (max(a, c), max(b, d))


def _minimum_iv(node, iv):
    (a, b), (c, d) = (iv + [UNKNOWN, UNKNOWN])[:2]
    return (min(a, c), min(b, d))


def _scalar_iv(fn):
    def tf(node, iv):
        s = node_attr(node, "scalar")
        if s is None:
            return UNKNOWN
        return fn(_first(iv), float(s))
    return tf


def _dropout_iv(node, iv):
    # train mode rescales kept units by 1/(1-p); eval is the identity.
    # The union of both covers either mode, keeping the transfer mode-free.
    lo, hi = _first(iv)
    try:
        scale = 1.0 / max(1.0 - float(node_attr(node, "p", 0.5)), 1e-6)
    except (TypeError, ValueError):
        return UNKNOWN
    slo, shi = _mul_iv((lo, hi), (scale, scale))
    return (min(lo, slo), max(hi, shi))


_CONST_RANGE = {
    "sigmoid": _UNIT, "hard_sigmoid": _UNIT, "softmax": _UNIT,
    "softmin": _UNIT, "SoftmaxActivation": _UNIT, "SoftmaxOutput": _UNIT,
    "tanh": _SYM1, "softsign": _SYM1, "erf": _SYM1, "sin": _SYM1,
    "cos": _SYM1, "L2Normalization": _SYM1,
    "BatchNorm": _NORMED, "LayerNorm": _NORMED, "InstanceNorm": _NORMED,
    "_bn_affine": _NORMED,
    "_zeros": (0.0, 0.0), "_zeros_like": (0.0, 0.0),
    "_ones": (1.0, 1.0), "_ones_like": (1.0, 1.0),
}

_PASSTHROUGH_OPS = frozenset({
    "Flatten", "Reshape", "reshape", "transpose", "SwapAxis", "slice",
    "slice_axis", "slice_like", "SliceChannel", "Crop", "expand_dims",
    "squeeze", "_copy", "identity", "BlockGrad", "stop_gradient", "cast",
    "Cast", "_precision_cast",
    "broadcast_to", "broadcast_axis", "broadcast_like", "tile",
    "repeat", "reverse", "sort", "UpSampling", "Pad", "mean",
    "max", "min", "take", "batch_take", "pick", "where", "depth_to_space",
    "space_to_depth", "gather_nd", "SequenceLast", "SequenceReverse",
})

def _pooling_iv(node, iv):
    # max/min/avg pooling stays inside the input interval; sum and lp
    # pooling ((sum |x|^p)^(1/p)) scale with the window — unbounded
    if node_attr(node, "pool_type", "max") in ("sum", "lp"):
        return UNKNOWN
    return _first(iv)


_IVAL_FNS = {
    "Activation": _activation_iv, "relu": _relu_iv, "clip": _clip_iv,
    "Pooling": _pooling_iv,
    "exp": _exp_iv, "log": _log_iv, "square": _square_iv, "sqrt": _sqrt_iv,
    "abs": lambda node, iv: (0.0, max(abs(_first(iv)[0]),
                                      abs(_first(iv)[1]))),
    "elemwise_add": _add_iv, "broadcast_add": _add_iv,
    "add_n": lambda node, iv: ((sum(lo for lo, _ in iv),
                                sum(hi for _, hi in iv)) if iv else UNKNOWN),
    "elemwise_sub": _sub_iv, "broadcast_sub": _sub_iv,
    "elemwise_mul": _binmul_iv, "broadcast_mul": _binmul_iv,
    "_maximum": _maximum_iv, "broadcast_maximum": _maximum_iv,
    "_minimum": _minimum_iv, "broadcast_minimum": _minimum_iv,
    "Concat": lambda node, iv: _widest(iv),
    "Dropout": _dropout_iv,
    "_plus_scalar": _scalar_iv(lambda a, s: (a[0] + s, a[1] + s)),
    "_minus_scalar": _scalar_iv(lambda a, s: (a[0] - s, a[1] - s)),
    "_rminus_scalar": _scalar_iv(lambda a, s: (s - a[1], s - a[0])),
    "_mul_scalar": _scalar_iv(lambda a, s: _mul_iv(a, (s, s))),
    "_div_scalar": _scalar_iv(
        lambda a, s: _mul_iv(a, (1.0 / s, 1.0 / s)) if s else UNKNOWN),
    "_maximum_scalar": _scalar_iv(
        lambda a, s: (max(a[0], s), max(a[1], s))),
    "_minimum_scalar": _scalar_iv(
        lambda a, s: (min(a[0], s), min(a[1], s))),
}


def _node_interval(node, in_ivals):
    """Output interval of one plan node given its inputs' intervals —
    sound-but-loose: anything unlisted is UNKNOWN."""
    opname = getattr(node.op, "name", "")
    fixed = _CONST_RANGE.get(opname)
    if fixed is not None:
        return fixed
    fn = _IVAL_FNS.get(opname)
    if fn is not None:
        try:
            lo, hi = fn(node, in_ivals)
        except (TypeError, ValueError, OverflowError):
            return UNKNOWN
        if math.isnan(lo) or math.isnan(hi) or lo > hi:
            return UNKNOWN
        return (lo, hi)
    if opname in _PASSTHROUGH_OPS:
        return _widest(in_ivals)
    return UNKNOWN


# -- the flow analysis --------------------------------------------------------

def _exp_range_safe(opname, interval):
    """May this exp/log-family node drop to bf16, given the input range
    interval analysis proved?  Unbounded -> never."""
    if opname in _JOINT_POWER:
        return False
    lo, hi = interval
    if not (math.isfinite(lo) and math.isfinite(hi)):
        return False
    if opname in _LOG_LIKE:
        return lo >= _LOG_SAFE_LO
    if opname in _SHIFT_INVARIANT:
        return (hi - lo) <= 2.0 * _EXP_SAFE
    return -_EXP_SAFE <= lo and hi <= _EXP_SAFE


def _const_interval(value):
    """Actual min/max of a baked constant — concrete host data, so this is
    a real (not abstract) range seed.  Large arrays skipped: scanning a
    folded weight tensor is not worth the host time."""
    import numpy as np

    try:
        arr = np.asarray(value)
    except Exception:
        return UNKNOWN
    if arr.size == 0 or arr.size > 65536 or arr.dtype.kind not in "fiu":
        return UNKNOWN
    lo, hi = float(arr.min()), float(arr.max())
    if math.isnan(lo) or math.isnan(hi):
        return UNKNOWN
    return (lo, hi)


def _flow(ctx, graph):
    """Run the dtype/interval/sensitivity analysis over ``graph`` ->
    ``(rows, diags)`` where ``rows`` is one cast-plan row per node in plan
    order and ``diags`` the hazard diagnostics.  Memoized per (ctx, graph):
    the registered analyzer and :func:`precision_plan` each need half of
    the result, and a shared context (the serving warmup path) must pay
    the abstract walk once, not twice."""
    import numpy as np

    from .graph_analyzers import _abstract_walk

    memo = getattr(ctx, "_numerics_flow", None)
    if memo is not None and memo[0] is graph:
        return memo[1]

    f64 = np.dtype("float64")
    ivals = {}          # env name -> (lo, hi)
    f64_origin = {}     # env name -> origin label for float64 taint
    creep = {}          # origin label -> [downstream node names]
    rows = []
    diags = []
    seen_nodes = set()  # multi-output nodes record once per output

    for name, aval in list(ctx.arg_avals.items()) + \
            list(ctx.aux_avals.items()):
        ivals[name] = UNKNOWN
        if aval.dtype == f64:
            f64_origin[name] = "input %r" % name
            creep.setdefault("input %r" % name, [])
    for name, value in graph.constants.items():
        ivals[name] = _const_interval(value)

    def record(node, nm, shape, dtype, in_vals, in_names):
        in_ivals = [ivals.get(n, UNKNOWN) for n in in_names]
        interval = _node_interval(node, in_ivals)
        ivals[nm] = interval
        opname = getattr(node.op, "name", "?")
        sens = op_sensitivity(node)

        in_fbits = [(n, _float_bits(getattr(v, "dtype", None)))
                    for n, v in zip(in_names, in_vals)]
        in_fbits = [(n, b) for n, b in in_fbits if b is not None]
        out_bits = _float_bits(dtype)

        # f64 creep: taint flows from the first float64 source downstream;
        # a node MAKING f64 out of narrower inputs is a new origin (the
        # shape_dtype analyzer flags that node itself as f64-promotion —
        # this analysis adds how far the poison spreads)
        if dtype == f64:
            origins = sorted({f64_origin[n] for n in in_names
                              if n in f64_origin})
            if origins:
                f64_origin[nm] = origins[0]
                if node.name not in creep.setdefault(origins[0], []):
                    creep[origins[0]].append(node.name)
            else:
                f64_origin[nm] = "node %r (%s)" % (node.name, opname)
                creep.setdefault(f64_origin[nm], [])

        first = node.name not in seen_nodes
        seen_nodes.add(node.name)
        if first:
            # silent downcast: output narrower than the widest float input
            # without an explicit cast op saying so
            if out_bits is not None and in_fbits \
                    and opname not in _EXPLICIT_CASTS \
                    and opname not in _PRECISION_MANAGED:
                widest_n, widest_b = max(in_fbits, key=lambda nb: nb[1])
                if out_bits < widest_b:
                    diags.append(Diagnostic(
                        "silent-downcast", WARNING,
                        "node %r (%s) narrows %s (%d-bit, via %r) to "
                        "%d-bit %s with no explicit cast — precision is "
                        "dropped where no reader of the graph can see it"
                        % (node.name, opname, widest_n, widest_b, widest_n,
                           out_bits, dtype), where=node.name))
            # mixed-dtype binop promotion
            float_dts = sorted({str(getattr(v, "dtype", ""))
                                for v in in_vals
                                if _float_bits(getattr(v, "dtype", None))
                                is not None})
            if len(float_dts) > 1 and opname not in _PRECISION_MANAGED \
                    and opname not in _EXPLICIT_CASTS:
                diags.append(Diagnostic(
                    "mixed-dtype-binop", WARNING,
                    "node %r (%s) mixes float input dtypes %s — jax "
                    "silently promotes to the widest; make the cast "
                    "explicit so the intent is reviewable"
                    % (node.name, opname, float_dts), where=node.name))
            # low-precision accumulation (jnp reductions accumulate in the
            # input dtype; MXU contractions accumulate fp32 in hardware)
            if sens in (REDUCE, CANCELLATION) \
                    and opname not in _MXU_ACCUM \
                    and any(b is not None and b <= 16 for _, b in in_fbits):
                diags.append(Diagnostic(
                    "low-precision-accum", WARNING,
                    "node %r (%s) accumulates over %d-bit float inputs — "
                    "each add loses one part in 256; keep an fp32 "
                    "accumulator (the bf16 cast pass must not lower this "
                    "node's reduction dtype)"
                    % (node.name, opname,
                       min(b for _, b in in_fbits if b is not None)),
                    where=node.name))
            # exp/log family reached by an unbounded range in low precision
            if sens == EXP_RANGE \
                    and not _exp_range_safe(opname, _widest(in_ivals)) \
                    and any(b is not None and b <= 16 for _, b in in_fbits):
                diags.append(Diagnostic(
                    "exp-unbounded-lowp", WARNING,
                    "node %r (%s) applies an exp/log-family function to a "
                    "%s-range %s input — bf16/f16 saturates or loses all "
                    "relative precision here; keep this node fp32"
                    % (node.name, opname,
                       "unbounded" if not all(map(
                           math.isfinite, _widest(in_ivals))) else "wide",
                       "/".join(sorted({str(getattr(v, "dtype", "?"))
                                        for v in in_vals
                                        if _is_lowp(getattr(v, "dtype",
                                                            None))}))),
                    where=node.name))
            # the verdict row
            if sens in (REDUCE, CANCELLATION):
                verdict = FP32_ACCUM
            elif sens == EXP_RANGE:
                verdict = BF16_SAFE if _exp_range_safe(
                    opname, _widest(in_ivals)) else FP32_ONLY
            else:
                verdict = BF16_SAFE
            rows.append({"node": node.name, "op": opname,
                         "sensitivity": sens, "verdict": verdict,
                         "dtype": str(dtype)})

    _abstract_walk(graph, ctx, record=record)

    for origin, downstream in sorted(creep.items()):
        if not downstream:
            # taint that never spread: an f64 input immediately cast away,
            # or a terminal promoting node (which shape_dtype already
            # flags as f64-promotion) — nothing flow-level to add
            continue
        diags.append(Diagnostic(
            "f64-creep", WARNING,
            "float64 originates at %s and flows through %d downstream "
            "node(s): %s — every tainted buffer is 2x memory and breaks "
            "TPU lowering; cast at the origin, not downstream"
            % (origin, len(downstream), ", ".join(downstream[:8])),
            where=origin))
    try:
        ctx._numerics_flow = (graph, (rows, diags))
    except AttributeError:
        pass  # a foreign ctx without the memo slot still analyzes fine
    return rows, diags


# -- the registered analyzer --------------------------------------------------

@register_analyzer("numerics", version=NUMERICS_VERSION)
def numerics(ctx):
    """Dtype-flow + sensitivity hazards over the plan actually lowered."""
    from .graph_analyzers import skipped_no_avals

    if not ctx.has_avals:
        return [skipped_no_avals("numerics")]
    _, diags = _flow(ctx, ctx.graph)
    return diags


# -- the cast-plan contract ---------------------------------------------------

class CastPlan:
    """The fingerprinted artifact the bf16-cast pass (ROADMAP item 3)
    consumes: one verdict row per plan node, in plan order.

    ``rows``     tuple of ``{"node", "op", "sensitivity", "verdict",
                 "dtype"}`` dicts;
    ``mode``     "train" | "eval" (the plan the verdicts describe);
    ``versions`` ``(SENSITIVITY_VERSION, NUMERICS_VERSION)`` under which
                 the verdicts were computed.
    """

    __slots__ = ("mode", "rows", "versions")

    def __init__(self, mode, rows, versions=None):
        self.mode = mode
        self.rows = tuple(dict(r) for r in rows)
        self.versions = tuple(versions) if versions is not None \
            else (SENSITIVITY_VERSION, NUMERICS_VERSION)

    def counts(self):
        """Verdict histogram — the warmup-row / ``Engine.stats()``
        surface."""
        out = {BF16_SAFE: 0, FP32_ACCUM: 0, FP32_ONLY: 0}
        for r in self.rows:
            out[r["verdict"]] = out.get(r["verdict"], 0) + 1
        return out

    def verdict(self, node_name):
        """Verdict for one node name, or None if the plan has no such
        node (e.g. it was folded away by the pass pipeline)."""
        for r in self.rows:
            if r["node"] == node_name:
                return r["verdict"]
        return None

    def fingerprint(self):
        """Stable identity of this plan's numerics contract: changes when
        and only when the verdict rows (i.e. the plan) or the registry /
        analyzer versions change."""
        blob = json.dumps({"mode": self.mode, "versions": self.versions,
                           "rows": self.rows}, sort_keys=True)
        return "castplan-" + hashlib.sha256(blob.encode()).hexdigest()[:16]

    def to_dict(self):
        """JSON-ready form (flight-recorder dumps, artifact files)."""
        return {"mode": self.mode, "fingerprint": self.fingerprint(),
                "versions": list(self.versions),
                "counts": self.counts(), "rows": [dict(r) for r in self.rows]}

    def __repr__(self):
        c = self.counts()
        return "CastPlan(%s, %d nodes: %d bf16_safe / %d fp32_accum / " \
            "%d fp32_only, %s)" % (self.mode, len(self.rows), c[BF16_SAFE],
                                   c[FP32_ACCUM], c[FP32_ONLY],
                                   self.fingerprint())


def precision_plan(ctx):
    """Compute the :class:`CastPlan` for a bound :class:`GraphContext` —
    the implementation behind ``Executor.precision_plan()`` /
    ``Predictor.precision_plan()``.  Raises ``ValueError`` when the
    context carries no avals: a cast plan over unknown dtypes would be a
    guess, and this artifact is a contract."""
    if not ctx.has_avals:
        raise ValueError(
            "precision_plan needs bound shapes/dtypes (arg_avals/aux_avals)"
            " — bind arrays before asking for a cast plan")
    rows, _ = _flow(ctx, ctx.graph)
    return CastPlan("train" if ctx.is_train else "eval", rows)


_VERDICT_RANK = {BF16_SAFE: 0, FP32_ACCUM: 1, FP32_ONLY: 2}


def param_verdict_classes(ctx):
    """{bound arg/aux name -> verdict class} for every input the plan
    consumes — the ISSUE 12 runtime export: each parameter takes the most
    conservative verdict (``fp32_only`` > ``fp32_accum`` > ``bf16_safe``)
    among the nodes that read it, so the trainhealth plane can bucket a
    runtime non-finite gradient by the class the static analyzer assigned
    to the parameter's compute.  Names never consumed by a classified node
    (dead inputs, pass-folded consumers) are simply absent — the caller
    reports them as "unknown", never as blessed.  Shares :func:`_flow`'s
    per-context memo with the analyzer and ``precision_plan`` (one
    abstract walk for all three); raises ``ValueError`` without bound
    avals, exactly like ``precision_plan``."""
    if not ctx.has_avals:
        raise ValueError(
            "param_verdict_classes needs bound shapes/dtypes "
            "(arg_avals/aux_avals) — bind arrays before asking for "
            "verdict classes")
    rows, _ = _flow(ctx, ctx.graph)
    by_node = {r["node"]: r["verdict"] for r in rows}
    bound = set(ctx.arg_names or ()) | set(ctx.aux_names or ())
    out = {}
    for node, in_names in ctx.graph.entries:
        v = by_node.get(node.name)
        if v is None:
            continue
        for n in in_names:
            if n in bound:
                cur = out.get(n)
                if cur is None or _VERDICT_RANK[v] > _VERDICT_RANK[cur]:
                    out[n] = v
    return out


def contract_fingerprint():
    """Version-only identity of the numerics contract, folded into the
    AOT-cache environment fingerprint (``compile_cache._env_fingerprint``)
    exactly like ``graph_passes.pipeline_fingerprint()``: any cast plan's
    fingerprint changes only when its plan changes (already keyed via the
    symbol + pass fingerprints) or when these versions bump — so keying
    the versions suffices to keep persisted executables honest once the
    bf16 pass starts rewriting plans from CastPlans."""
    return "numerics:%d|sensitivity:%d" % (NUMERICS_VERSION,
                                           SENSITIVITY_VERSION)
