"""Graph-IR analyzers (ISSUE 8, layer 1) — pure ``ctx -> [Diagnostic]``
checks over the execution-plan IR, registered in run order:

1. ``prng_safety``   — every stochastic node must fold a *distinct* PRNG
   stream.  ``Executor._graph_fn`` keys each stream by the node's NAME
   (``fold_in(key, crc32(name))``), so two stochastic nodes sharing a name
   (or an identical explicit ``key`` attr) silently draw correlated — the
   exact hazard ``common_subexpr_merge`` must never introduce.  Also flags
   a stochastic node that stays LIVE in an eval plan (samples at inference:
   ``mode="always"`` dropout, rrelu, ``random_*`` sources) — legitimate for
   MC-dropout, surprising everywhere else, so a warning, not an error.
2. ``shape_dtype``   — abstract walk of the plan via ``jax.eval_shape`` (no
   compile, no device work): flags float64 node outputs whose inputs were
   all narrower (silent x64 promotion inflates memory 2x and breaks TPU
   lowering), and any head whose shape/dtype DRIFTED between the captured
   plan and the pass-optimized plan — the invariant every registered pass
   must preserve.  A context without bound avals degrades to one INFO
   (``analyzer-skipped``) so the skip is visible in ``check()`` output and
   warmup rows, never silent (ISSUE 11).
3. ``dead_code``     — arguments and aux states no surviving plan node
   consumes: dead weight being staged to device every forward, usually a
   sign the graph author kept a head they meant to drop.
4. ``numerics``      — dtype-flow + numeric-sensitivity analysis
   (``numerics.py``): silent downcasts, mixed-dtype promotions, f64 creep
   with the originating node named, low-precision accumulation, and the
   per-node ``bf16_safe | fp32_accum | fp32_only`` cast-plan verdicts
   (ISSUE 11).

Analyzers never mutate the Graph and never raise through ``analyze`` — a
failing analyzer degrades to one INFO diagnostic (manager contract).
"""
from __future__ import annotations

import zlib

from ..graph_passes.ir import node_attr, node_call_attrs, node_out_names
from . import register_analyzer
from .diagnostics import Diagnostic, ERROR, INFO, WARNING

__all__ = ["prng_safety", "shape_dtype", "dead_code", "skipped_no_avals"]


def skipped_no_avals(analyzer):
    """The one ``analyzer-skipped`` INFO shape (ISSUE 11 satellite): a
    context without bound avals used to skip the abstract-walk analyzers
    SILENTLY — now the skip is a visible diagnostic, so a warmup row (or a
    ``check()`` caller) can tell "clean" apart from "never looked"."""
    return Diagnostic(
        "analyzer-skipped", INFO,
        "%s skipped: context carries no bound avals (shapes/dtypes "
        "unknown) — bind arrays, or build the GraphContext with "
        "arg_avals/aux_avals, to run the abstract walk" % analyzer,
        analyzer=analyzer)


def _stochastic(node):
    return "key" in getattr(node.op, "attr_names", ())


def _eval_live(node):
    """Does this stochastic node actually DRAW in an eval plan?  Dropout is
    the identity at eval unless forced (``mode="always"`` / an explicit
    ``training`` attr); rrelu and the ``random_*`` sources sample whenever
    they get a key — which ``_graph_fn`` always folds in."""
    opname = getattr(node.op, "name", "")
    if opname == "Dropout":
        return bool(node.attrs.get("training")) \
            or node_attr(node, "mode") == "always"
    if opname == "LeakyReLU":
        return node_attr(node, "act_type") == "rrelu"
    return True


@register_analyzer("prng_safety", version=1)
def prng_safety(ctx):
    """Shared-stream + eval-plan-stochastic checks over the lowered plan."""
    streams = {}  # stream id -> [node name]
    diags = []
    for node, _ in ctx.graph.entries:
        if not _stochastic(node):
            continue
        if "key" in node.attrs:
            sid = ("explicit", repr(node.attrs["key"]))
        else:
            sid = ("name", zlib.crc32(node.name.encode()))
        streams.setdefault(sid, []).append(node.name)
        if not ctx.is_train and _eval_live(node):
            diags.append(Diagnostic(
                "prng-eval-stochastic", WARNING,
                "stochastic node %r (%s) samples in an EVAL plan — "
                "inference outputs are nondeterministic (intended only for "
                "MC-dropout-style deployments)"
                % (node.name, getattr(node.op, "name", "?")),
                where=node.name))
    for (kind, _), names in streams.items():
        if len(names) > 1:
            diags.append(Diagnostic(
                "prng-shared-stream", ERROR,
                "stochastic nodes %s fold the SAME PRNG stream (%s) — "
                "their draws are identical, silently correlating what "
                "should be independent randomness"
                % (sorted(names),
                   "shared explicit key attr" if kind == "explicit"
                   else "same node name, same fold_in"),
                where=",".join(sorted(set(names)))))
    return diags


def _abstract_walk(graph, ctx, record=None):
    """``jax.eval_shape`` the plan exactly as ``Executor._graph_fn`` would
    evaluate it (same attr fill-in for ``key``/``training``, same
    hidden-output trim, aux updates skipped — heads don't consume them)
    -> [head ShapeDtypeStruct].  ``record(node, out_name, shape, dtype,
    in_vals, in_names)`` observes every node output during the abstract
    trace (``in_names`` are the env names feeding the node — the numerics
    analyzer keys its interval environment on them)."""
    import jax
    import numpy as np

    arg_avals = [ctx.arg_avals[n] for n in ctx.arg_names]
    aux_avals = [ctx.aux_avals[n] for n in ctx.aux_names]
    entries, heads = graph.entries, graph.heads
    consts = graph.constants

    def f(arg_vals, aux_vals, key):
        env = dict(consts) if consts else {}
        env.update(zip(ctx.arg_names, arg_vals))
        env.update(zip(ctx.aux_names, aux_vals))
        for node, in_names in entries:
            attrs = node_call_attrs(node, key, ctx.is_train)
            res = node.op.fn(*[env[n] for n in in_names], **attrs)
            outs = res if isinstance(res, tuple) else (res,)
            if len(outs) > 1 and node.num_outputs == 1:
                outs = outs[:1]
            for nm, o in zip(node_out_names(node), outs):
                env[nm] = o
                if record is not None:
                    # shape/dtype of an abstract tracer are concrete
                    record(node, nm, tuple(o.shape), o.dtype,
                           [env[n] for n in in_names], in_names)
        return [env[h] for h in heads]

    return jax.eval_shape(f, arg_avals, aux_avals,
                          jax.ShapeDtypeStruct((2,), np.uint32))


@register_analyzer("shape_dtype", version=1)
def shape_dtype(ctx):
    """f64-promotion + raw-vs-optimized head drift, via jax.eval_shape."""
    import numpy as np

    if not ctx.has_avals:
        return [skipped_no_avals("shape_dtype")]
    diags = []
    f64 = np.dtype("float64")

    def record(node, nm, shape, dtype, in_vals, in_names):
        if dtype == f64 and not any(
                getattr(v, "dtype", None) == f64 for v in in_vals):
            diags.append(Diagnostic(
                "f64-promotion", WARNING,
                "node %r (%s) output %s promotes to float64 with no "
                "float64 input — a silent x64 upcast (check python-scalar "
                "attrs / np constants in the op)"
                % (node.name, getattr(node.op, "name", "?"), nm),
                where=nm))

    opt_heads = _abstract_walk(ctx.graph, ctx, record=record)
    if ctx.raw is not ctx.graph:
        raw_heads = _abstract_walk(ctx.raw, ctx)
        if len(raw_heads) != len(opt_heads):
            diags.append(Diagnostic(
                "pass-drift", ERROR,
                "head COUNT drifted across the pass pipeline: captured %d "
                "-> optimized %d — a registered pass dropped or invented "
                "an output" % (len(raw_heads), len(opt_heads)),
                where="heads"))
        for i, (r, o) in enumerate(zip(raw_heads, opt_heads)):
            if tuple(r.shape) != tuple(o.shape) or r.dtype != o.dtype:
                diags.append(Diagnostic(
                    "pass-drift", ERROR,
                    "head %d drifted across the pass pipeline: captured "
                    "%s%s -> optimized %s%s — a registered pass broke the "
                    "plan contract"
                    % (i, r.dtype, tuple(r.shape), o.dtype, tuple(o.shape)),
                    where="head%d" % i))
    return diags


@register_analyzer("dead_code", version=1)
def dead_code(ctx):
    """Unused-input / dead-aux detection over the plan actually lowered."""
    if ctx.arg_names is None:
        return []
    used = set(ctx.graph.heads)
    for _, in_names in ctx.graph.entries:
        used.update(in_names)
    diags = []
    for n in ctx.arg_names:
        if n not in used:
            diags.append(Diagnostic(
                "unused-input", WARNING,
                "argument %r is consumed by no node in the %s plan — it is "
                "staged to device every forward for nothing"
                % (n, "train" if ctx.is_train else "eval"), where=n))
    for n in ctx.aux_names or ():
        if n not in used:
            diags.append(Diagnostic(
                "dead-aux", WARNING,
                "aux state %r is consumed by no node in the %s plan"
                % (n, "train" if ctx.is_train else "eval"), where=n))
    return diags
