"""mxnet_tpu.analysis — static analysis & contract checking (ISSUE 8).

Three cooperating layers, all off by default, each catching a bug class this
repo has previously found only by stress-bisection:

1. **Graph-IR analyzers** (``graph_analyzers.py``) — pure functions over the
   ``graph_passes.ir.Graph`` execution plan, run through the analyzer
   manager below (a mirror of the pass manager: registration order is run
   order, (name, version) identity).  They check the *contracts the pass
   pipeline must preserve*: distinct PRNG streams per stochastic node, no
   live stochastic node in an eval plan, no shape/dtype drift between the
   captured and the optimized plan (via ``jax.eval_shape`` — abstract, no
   compile), no silently dead inputs/aux — and, since ISSUE 11, the
   precision-flow hazards (``numerics.py``: silent downcasts, mixed-dtype
   promotions, f64 creep, low-precision accumulation) behind the
   ``bf16_safe | fp32_accum | fp32_only`` cast-plan verdicts ROADMAP item
   3's bf16 pass will consume (``Executor.precision_plan()``).  Surfaced as
   ``Executor.check()`` / ``Predictor.check()`` (always available) and as
   per-bucket warning counts in serving warmup report rows (gated on
   ``MXNET_GRAPH_ANALYZERS``).
2. **JAX-hazard source lint** (``source_lint.py``, CLI ``tools/mxlint.py``)
   — AST lint over the codebase itself for host-sync/retrace hazards inside
   traced functions, with a committed baseline so justified sites are
   suppressed explicitly.
3. **Lock-discipline checker** (``lockcheck.py``, ``MXNET_LOCKCHECK=1``) —
   wraps the serving engine's mutexes, detects lock-order inversions and
   unguarded mutation of lock-owned state, reports via
   ``lockcheck_violations_total{kind}`` and raises under pytest.

Relay/TVM ship their IRs with validity checks at every lowering layer
(PAPERS.md 1810.00952, 1802.04799); this package is that layer for ours.
"""
from __future__ import annotations

from ..base import env_flag
from .diagnostics import Diagnostic, ERROR, INFO, WARNING, worst_severity

__all__ = ["Diagnostic", "ERROR", "WARNING", "INFO", "worst_severity",
           "enabled", "register_analyzer", "analyzer_pipeline", "analyze",
           "GraphContext", "check_executor", "precision_plan_executor"]

_ANALYZERS = []  # [(name, version, fn)] — registration order is run order


def enabled():
    """``MXNET_GRAPH_ANALYZERS`` gate (docs/ENV_VARS.md) — default OFF.

    Gates only the *automatic* surfaces (serving warmup report rows); an
    explicit ``Executor.check()`` / ``Predictor.check()`` call always runs,
    calling it being opt-in by definition."""
    return env_flag("MXNET_GRAPH_ANALYZERS")


def register_analyzer(name, version=1):
    """Register a pure ``fn(ctx) -> iterable[Diagnostic]`` graph analyzer.
    Mirrors ``graph_passes.register_pass``: registration order is run order
    and (name, version) is the analyzer's identity in reports."""
    def _reg(fn):
        _ANALYZERS.append((str(name), int(version), fn))
        return fn
    return _reg


def analyzer_pipeline():
    """The registered (name, version) analyzer list, in run order."""
    return tuple((n, v) for n, v, _ in _ANALYZERS)


def analyze(ctx):
    """Run every registered analyzer over ``ctx`` -> sorted [Diagnostic]
    (most severe first).  An analyzer that raises contributes one INFO
    diagnostic instead of failing the whole check — ``check()`` must be
    safe to call on any graph.  Every finding (all analyzers, the degraded
    INFO included) is counted into ``analysis_findings_total{analyzer,
    severity}`` when telemetry is on (ISSUE 11 satellite; the off path is
    one gate check inside ``note_analysis_finding``)."""
    from ..telemetry import note_analysis_finding

    out = []
    for name, version, fn in _ANALYZERS:
        try:
            diags = list(fn(ctx))
        except Exception as e:
            diags = [Diagnostic("analyzer-failed", INFO,
                                "analyzer %s:%d did not complete: %r"
                                % (name, version, e))]
        for d in diags:
            if d.analyzer is None:
                d.analyzer = name
        counts = {}
        for d in diags:
            counts[d.severity] = counts.get(d.severity, 0) + 1
        for severity, n in counts.items():
            note_analysis_finding(name, severity, n)
        out.extend(diags)
    out.sort(key=Diagnostic._sort_key)
    return out


class GraphContext:
    """Everything a graph analyzer may consult.

    ``graph``     the plan the executor actually lowers (pass-optimized when
                  ``MXNET_GRAPH_PASSES`` is on, raw otherwise);
    ``raw``       the captured pre-pass plan (drift checks compare the two);
    ``is_train``  the plan's mode;
    ``arg_names`` / ``aux_names``  bound argument/aux order, or None when
                  the context carries no executor;
    ``arg_avals`` / ``aux_avals``  name -> ``jax.ShapeDtypeStruct`` for the
                  bound arrays, or None when shapes are unknown — analyzers
                  needing abstract evaluation skip silently without them.
    """

    __slots__ = ("graph", "raw", "is_train", "arg_names", "aux_names",
                 "arg_avals", "aux_avals", "_numerics_flow")

    def __init__(self, graph, raw=None, is_train=False, arg_names=None,
                 aux_names=None, arg_avals=None, aux_avals=None):
        # per-context memo for numerics._flow (rows + diags come from ONE
        # abstract walk; analyze() and precision_plan() on the same ctx
        # share it — the serving warmup path relies on this)
        self._numerics_flow = None
        self.graph = graph
        self.raw = raw if raw is not None else graph
        self.is_train = bool(is_train)
        self.arg_names = list(arg_names) if arg_names is not None else None
        self.aux_names = list(aux_names) if aux_names is not None else None
        self.arg_avals = arg_avals
        self.aux_avals = aux_avals

    @property
    def has_avals(self):
        """Can the abstract-walk analyzers run?  The ONE definition of
        "bound": names plus both aval maps present — shape_dtype, the
        numerics analyzer, and ``precision_plan`` all key off this, so the
        ``analyzer-skipped`` contract cannot drift between them."""
        return (self.arg_names is not None and self.arg_avals is not None
                and self.aux_avals is not None)


def _avals_of(dicts, names):
    """name -> ShapeDtypeStruct for bound NDArrays; None if any is missing
    (the shape analyzer then skips — never guesses)."""
    import jax

    out = {}
    for n in names:
        arr = dicts.get(n)
        if arr is None:
            return None
        data = getattr(arr, "_data", arr)
        out[n] = jax.ShapeDtypeStruct(tuple(data.shape), data.dtype)
    return out


def executor_context(exe, is_train=False, plan="lowered"):
    """Build a :class:`GraphContext` over a bound Executor's plan for
    ``is_train`` — shared by :func:`check_executor` and
    :func:`precision_plan_executor`.

    ``plan="lowered"`` (default) describes what :meth:`Executor._graph_fn`
    actually evaluates — precision-tier rewrites included (ISSUE 15), so
    ``check()`` diagnoses the twin a tier executor really compiles.
    ``plan="structural"`` stops after the standard pipeline — the fp32
    graph the tier passes rewrite, which is what the CastPlan contract
    (``precision_plan``) and the tier passes themselves are defined over;
    the two are identical on executors with no active tier."""
    from ..graph_passes import Graph

    if plan == "structural":
        plan, heads, const_env = exe._structural_plan(is_train)
    else:
        plan, heads, const_env = exe._opt_plan(is_train)
    # hand over the raw plan only when the pass pipeline actually produced
    # a different one (gate off ⇒ _opt_plan returns exe._plan itself):
    # the drift check can never fire on an identical plan, and skipping it
    # halves the abstract-walk cost of check() on the off path
    raw = None if plan is exe._plan else Graph(exe._plan, exe._head_names)
    return GraphContext(
        Graph(plan, heads, const_env),
        raw=raw,
        is_train=is_train,
        arg_names=exe._arg_names, aux_names=exe._aux_names,
        arg_avals=_avals_of(exe.arg_dict, exe._arg_names),
        aux_avals=_avals_of(exe.aux_dict, exe._aux_names))


def check_executor(exe, is_train=False):
    """Run the registered analyzers over a bound Executor's plan — the
    implementation behind ``Executor.check()``/``Predictor.check()``."""
    return analyze(executor_context(exe, is_train))


def precision_plan_executor(exe, is_train=False):
    """The :class:`numerics.CastPlan` for a bound Executor's plan — the
    implementation behind ``Executor.precision_plan()`` /
    ``Predictor.precision_plan()`` (ISSUE 11).  Always computed over the
    STRUCTURAL (pre-precision-tier) plan: the CastPlan is the decision
    artifact the tier passes consume (ISSUE 15), so it must describe the
    fp32 graph being rewritten, not the rewrite's own output."""
    from . import numerics as _numerics

    return _numerics.precision_plan(
        executor_context(exe, is_train, plan="structural"))


from . import graph_analyzers  # noqa: E402,F401  (registers the analyzers)
from . import numerics  # noqa: E402,F401  (registers the numerics analyzer)
