"""mxnet_tpu.analysis — static analysis & contract checking (ISSUE 8).

Three cooperating layers, all off by default, each catching a bug class this
repo has previously found only by stress-bisection:

1. **Graph-IR analyzers** (``graph_analyzers.py``) — pure functions over the
   ``graph_passes.ir.Graph`` execution plan, run through the analyzer
   manager below (a mirror of the pass manager: registration order is run
   order, (name, version) identity).  They check the *contracts the pass
   pipeline must preserve*: distinct PRNG streams per stochastic node, no
   live stochastic node in an eval plan, no shape/dtype drift between the
   captured and the optimized plan (via ``jax.eval_shape`` — abstract, no
   compile), no silently dead inputs/aux.  Surfaced as
   ``Executor.check()`` / ``Predictor.check()`` (always available) and as
   per-bucket warning counts in serving warmup report rows (gated on
   ``MXNET_GRAPH_ANALYZERS``).
2. **JAX-hazard source lint** (``source_lint.py``, CLI ``tools/mxlint.py``)
   — AST lint over the codebase itself for host-sync/retrace hazards inside
   traced functions, with a committed baseline so justified sites are
   suppressed explicitly.
3. **Lock-discipline checker** (``lockcheck.py``, ``MXNET_LOCKCHECK=1``) —
   wraps the serving engine's mutexes, detects lock-order inversions and
   unguarded mutation of lock-owned state, reports via
   ``lockcheck_violations_total{kind}`` and raises under pytest.

Relay/TVM ship their IRs with validity checks at every lowering layer
(PAPERS.md 1810.00952, 1802.04799); this package is that layer for ours.
"""
from __future__ import annotations

from ..base import env_flag
from .diagnostics import Diagnostic, ERROR, INFO, WARNING, worst_severity

__all__ = ["Diagnostic", "ERROR", "WARNING", "INFO", "worst_severity",
           "enabled", "register_analyzer", "analyzer_pipeline", "analyze",
           "GraphContext", "check_executor"]

_ANALYZERS = []  # [(name, version, fn)] — registration order is run order


def enabled():
    """``MXNET_GRAPH_ANALYZERS`` gate (docs/ENV_VARS.md) — default OFF.

    Gates only the *automatic* surfaces (serving warmup report rows); an
    explicit ``Executor.check()`` / ``Predictor.check()`` call always runs,
    calling it being opt-in by definition."""
    return env_flag("MXNET_GRAPH_ANALYZERS")


def register_analyzer(name, version=1):
    """Register a pure ``fn(ctx) -> iterable[Diagnostic]`` graph analyzer.
    Mirrors ``graph_passes.register_pass``: registration order is run order
    and (name, version) is the analyzer's identity in reports."""
    def _reg(fn):
        _ANALYZERS.append((str(name), int(version), fn))
        return fn
    return _reg


def analyzer_pipeline():
    """The registered (name, version) analyzer list, in run order."""
    return tuple((n, v) for n, v, _ in _ANALYZERS)


def analyze(ctx):
    """Run every registered analyzer over ``ctx`` -> sorted [Diagnostic]
    (most severe first).  An analyzer that raises contributes one INFO
    diagnostic instead of failing the whole check — ``check()`` must be
    safe to call on any graph."""
    out = []
    for name, version, fn in _ANALYZERS:
        try:
            diags = list(fn(ctx))
        except Exception as e:
            diags = [Diagnostic("analyzer-failed", INFO,
                                "analyzer %s:%d did not complete: %r"
                                % (name, version, e))]
        for d in diags:
            if d.analyzer is None:
                d.analyzer = name
        out.extend(diags)
    out.sort(key=Diagnostic._sort_key)
    return out


class GraphContext:
    """Everything a graph analyzer may consult.

    ``graph``     the plan the executor actually lowers (pass-optimized when
                  ``MXNET_GRAPH_PASSES`` is on, raw otherwise);
    ``raw``       the captured pre-pass plan (drift checks compare the two);
    ``is_train``  the plan's mode;
    ``arg_names`` / ``aux_names``  bound argument/aux order, or None when
                  the context carries no executor;
    ``arg_avals`` / ``aux_avals``  name -> ``jax.ShapeDtypeStruct`` for the
                  bound arrays, or None when shapes are unknown — analyzers
                  needing abstract evaluation skip silently without them.
    """

    __slots__ = ("graph", "raw", "is_train", "arg_names", "aux_names",
                 "arg_avals", "aux_avals")

    def __init__(self, graph, raw=None, is_train=False, arg_names=None,
                 aux_names=None, arg_avals=None, aux_avals=None):
        self.graph = graph
        self.raw = raw if raw is not None else graph
        self.is_train = bool(is_train)
        self.arg_names = list(arg_names) if arg_names is not None else None
        self.aux_names = list(aux_names) if aux_names is not None else None
        self.arg_avals = arg_avals
        self.aux_avals = aux_avals


def _avals_of(dicts, names):
    """name -> ShapeDtypeStruct for bound NDArrays; None if any is missing
    (the shape analyzer then skips — never guesses)."""
    import jax

    out = {}
    for n in names:
        arr = dicts.get(n)
        if arr is None:
            return None
        data = getattr(arr, "_data", arr)
        out[n] = jax.ShapeDtypeStruct(tuple(data.shape), data.dtype)
    return out


def check_executor(exe, is_train=False):
    """Build a :class:`GraphContext` from a bound Executor and run the
    registered analyzers over the plan it lowers for ``is_train`` — the
    implementation behind ``Executor.check()``/``Predictor.check()``."""
    from ..graph_passes import Graph

    plan, heads, const_env = exe._opt_plan(is_train)
    # hand over the raw plan only when the pass pipeline actually produced
    # a different one (gate off ⇒ _opt_plan returns exe._plan itself):
    # the drift check can never fire on an identical plan, and skipping it
    # halves the abstract-walk cost of check() on the off path
    raw = None if plan is exe._plan else Graph(exe._plan, exe._head_names)
    ctx = GraphContext(
        Graph(plan, heads, const_env),
        raw=raw,
        is_train=is_train,
        arg_names=exe._arg_names, aux_names=exe._aux_names,
        arg_avals=_avals_of(exe.arg_dict, exe._arg_names),
        aux_avals=_avals_of(exe.aux_dict, exe._aux_names))
    return analyze(ctx)


from . import graph_analyzers  # noqa: E402,F401  (registers the analyzers)
