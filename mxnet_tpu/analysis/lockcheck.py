"""Lock-discipline checker (ISSUE 8, layer 3) — ``MXNET_LOCKCHECK=1``.

The serving engine coordinates three hand-ordered mutexes
(``_cache_mu`` / ``_device_mu`` / ``_stats_mu``, ``serving/engine.py``) and
a set of containers each mutex owns.  The discipline is documented but was
never machine-checked: an inversion (thread A takes cache→stats while
thread B takes stats→cache) or a mutation slipped outside the owning lock
is exactly the class of bug this repo has only ever found by stress runs.

This module makes the discipline executable, three checks:

* **order**     — every :class:`CheckedLock` acquisition records the edge
  ``(already-held → acquiring)`` in a process-global order graph.  The
  first time both ``A→B`` and ``B→A`` exist the acquisition is flagged
  ``kind="inversion"`` (a potential deadlock, even if this run never
  interleaved badly — that is the point of checking statically observed
  order rather than waiting for the hang).
* **reentry**   — re-acquiring a non-reentrant lock the current thread
  already holds (``kind="reentry"``): a guaranteed self-deadlock.
* **ownership** — containers wrapped by :func:`guard` flag any mutating
  method called while the owning lock is NOT held by the calling thread
  (``kind="unguarded-mutation"``); :func:`instrument_fields` catches
  wholesale field *re-assignment* the same way (``self._warmup = {...}``
  outside ``_stats_mu``).

Reporting: every violation appends a ``Diagnostic`` to :func:`violations`,
increments ``lockcheck_violations_total{kind}`` (when telemetry is on), and
— under pytest (``PYTEST_CURRENT_TEST`` set) — raises
:class:`LockDisciplineError` so a seeded violation fails the test that
provoked it.  Outside pytest it prints to stderr and continues: a
production canary under ``MXNET_LOCKCHECK=1`` should record, not crash.
The exceptions are **reentry** and **bad-release**, which raise
everywhere — continuing past a reentry blocks forever on the
non-reentrant lock, and honoring a stray release strips the real
holder's ownership.

Off path: with the gate unset nothing here is ever imported by the engine
— the three mutexes stay vanilla ``threading.Lock`` objects and the
containers stay plain dicts/sets (asserted by
``tests/test_analysis.py::test_lockcheck_off_is_plain_locks``).
"""
from __future__ import annotations

import os
import sys
import threading

from ..base import env_flag
from .diagnostics import Diagnostic, ERROR

__all__ = ["enabled", "LockDisciplineError", "CheckedLock", "guard",
           "instrument_fields", "instrument_engine", "violations", "reset"]


def enabled():
    """``MXNET_LOCKCHECK`` gate (docs/ENV_VARS.md) — default OFF."""
    return env_flag("MXNET_LOCKCHECK")


class LockDisciplineError(AssertionError):
    """A lock-order / lock-ownership violation (raised only under pytest;
    recorded everywhere)."""


# process-global state: the order graph spans engines on purpose — two
# engine instances sharing a thread pool must still agree on lock order
_mu = threading.Lock()
_edges = {}        # before_name -> set(after_name): observed order graph
_violations = []   # [Diagnostic], append-only until reset()
_tls = threading.local()


def _held():
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


def _report(kind, message, where=None, fatal=False):
    """Record + count one violation.  Raises under pytest, or when
    ``fatal`` — continuing past a reentry would block forever on the
    non-reentrant lock, so raising is strictly better than the deadlock
    the canary just diagnosed; every other kind records and continues."""
    d = Diagnostic("lock-" + kind, ERROR, message, where=where,
                   analyzer="lockcheck")
    with _mu:
        _violations.append(d)
    from .. import telemetry

    telemetry.note_lockcheck_violation(kind)
    if fatal or "PYTEST_CURRENT_TEST" in os.environ:
        raise LockDisciplineError(str(d))
    print("lockcheck: %s" % d, file=sys.stderr)


def violations():
    """All violations recorded since process start (or :func:`reset`)."""
    with _mu:
        return list(_violations)


def reset():
    """Drop recorded violations AND the learned order graph (tests)."""
    with _mu:
        _violations.clear()
        _edges.clear()


def _path(src, dst):
    """Is ``dst`` reachable from ``src`` in the order graph (BFS over
    _edges)?  Returns the path as a name list, or None.  Caller holds _mu.
    Cycles of ANY length matter: A->B, B->C, C->A deadlocks three threads
    even though no direct reverse edge exists."""
    parents = {src: None}
    frontier = [src]
    while frontier:
        nxt = []
        for n in frontier:
            for m in _edges.get(n, ()):
                if m in parents:
                    continue
                parents[m] = n
                if m == dst:
                    out = [m]
                    while parents[out[-1]] is not None:
                        out.append(parents[out[-1]])
                    return out[::-1]
                nxt.append(m)
        frontier = nxt
    return None


class CheckedLock:
    """``threading.Lock`` drop-in that records per-thread acquisition order
    into the global graph and knows whether the *current* thread holds it
    (plain locks cannot answer that — the ownership checks need it)."""

    __slots__ = ("name", "_lock", "_owner")

    def __init__(self, name):
        self.name = str(name)
        self._lock = threading.Lock()
        self._owner = None  # ident of the holding thread, read racily is ok

    def held(self):
        """Does the CALLING thread hold this lock right now?"""
        return self._owner == threading.get_ident()

    def locked(self):
        return self._lock.locked()

    def acquire(self, blocking=True, timeout=-1):
        held = _held()
        if self.held():
            _report("reentry",
                    "thread %r re-acquires %s which it already holds — a "
                    "non-reentrant Lock self-deadlocks here"
                    % (threading.current_thread().name, self.name),
                    where=self.name, fatal=True)
        inverted = None
        # only unconditional blocking acquires enter the order graph —
        # trylock / timeout acquires cannot deadlock (the caller handles
        # failure), and recording them would poison the graph with edges
        # from deadlock-AVOIDANCE idioms (lockdep exempts trylocks too).
        # Recording happens BEFORE the acquire on purpose: the inversion
        # report must fire before the blocking call that would hang.
        if blocking and timeout == -1:
            with _mu:
                for prior in held:
                    succ = _edges.setdefault(prior.name, set())
                    if self.name in succ:
                        continue
                    # adding prior->self closes a cycle iff prior is
                    # already reachable FROM self — catches N-lock cycles
                    # (A->B, B->C, C->A), not just direct 2-lock reversals
                    cycle = _path(self.name, prior.name)
                    succ.add(self.name)
                    if cycle is not None and inverted is None:
                        inverted = (prior, cycle)
        if inverted is not None:
            prior, cycle = inverted
            _report("inversion",
                    "lock-order inversion: thread %r acquires %s while "
                    "holding %s, but the order %s was also observed — "
                    "threads interleaving these paths deadlock"
                    % (threading.current_thread().name, self.name,
                       prior.name, " -> ".join(cycle)),
                    where="%s<->%s" % (prior.name, self.name))
        ok = self._lock.acquire(blocking) if timeout == -1 \
            else self._lock.acquire(blocking, timeout)
        if ok:
            self._owner = threading.get_ident()
            held.append(self)
        return ok

    def release(self):
        if not self.held():
            # a cross-thread (or unmatched) release would silently strip
            # the real holder's ownership and misattribute the NEXT
            # guarded mutation — diagnose the stray release itself, and
            # refuse it so the holder's state stays truthful
            _report("bad-release",
                    "thread %r releases %s which it does not hold (owner: "
                    "thread ident %s) — a cross-thread or double release"
                    % (threading.current_thread().name, self.name,
                       self._owner), where=self.name, fatal=True)
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._owner = None
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return "CheckedLock(%s)" % self.name


# mutating methods across the container types the engine guards
# (dict / OrderedDict / set) — reads stay unchecked: the engine's
# documented discipline covers mutation, and e.g. stats() deliberately
# reads queue depth lock-free
_MUTATORS = frozenset({
    "update", "pop", "popitem", "clear", "setdefault",
    "add", "discard", "remove", "move_to_end",
    "append", "extend", "insert",
})


class _Guarded:
    """Container proxy checking the owning :class:`CheckedLock` on every
    mutating operation.  Delegates everything else; supports the mapping
    protocol (``dict(proxy)`` works via ``keys``/``__getitem__``)."""

    __slots__ = ("_obj", "_lock", "_name")

    def __init__(self, obj, lock, name):
        self._obj = obj
        self._lock = lock
        self._name = name

    def _check(self, op):
        if not self._lock.held():
            _report("unguarded-mutation",
                    "field %r mutated (%s) by thread %r without holding its "
                    "owning mutex %s"
                    % (self._name, op, threading.current_thread().name,
                       self._lock.name),
                    where="%s.%s" % (self._name, op))

    # -- mapping/sequence dunders (never reached via __getattr__) -----------
    def __getitem__(self, k):
        return self._obj[k]

    def __setitem__(self, k, v):
        self._check("__setitem__")
        self._obj[k] = v

    def __delitem__(self, k):
        self._check("__delitem__")
        del self._obj[k]

    def __contains__(self, k):
        return k in self._obj

    def __len__(self):
        return len(self._obj)

    def __iter__(self):
        return iter(self._obj)

    def __bool__(self):
        return bool(self._obj)

    def __repr__(self):
        return "Guarded(%s=%r)" % (self._name, self._obj)

    def __getattr__(self, attr):
        val = getattr(self._obj, attr)
        if attr in _MUTATORS:
            def checked(*a, **kw):
                self._check(attr)
                return val(*a, **kw)
            return checked
        return val


def guard(obj, lock, name):
    """Wrap a lock-owned container so unguarded mutation is a violation."""
    return _Guarded(obj, lock, name)


def instrument_fields(obj, owners):
    """Swap ``obj``'s class for a one-off subclass whose ``__setattr__``
    checks the owning lock for fields in ``owners`` (field name -> lock
    attribute name) — catching wholesale reassignment :func:`guard` cannot
    see.  ``isinstance(obj, OriginalClass)`` keeps holding."""
    owners = dict(owners)
    cls = obj.__class__

    def _setattr(self, name, value):
        lk_name = owners.get(name)
        if lk_name is not None:
            lk = self.__dict__.get(lk_name)
            if isinstance(lk, CheckedLock) and not lk.held():
                _report("unguarded-mutation",
                        "field %r reassigned by thread %r without holding "
                        "its owning mutex %s"
                        % (name, threading.current_thread().name, lk.name),
                        where=name)
        object.__setattr__(self, name, value)

    obj.__class__ = type("LockChecked" + cls.__name__, (cls,),
                         {"__setattr__": _setattr})
    return obj


def instrument_engine(engine):
    """Apply the full discipline to a serving ``Engine`` (called from its
    ``__init__`` when :func:`enabled`).  The ownership map is the one
    ``engine.py`` documents:

    ========== =========================================
    mutex      owns
    ========== =========================================
    _cache_mu  _cache, _direct_cache, _compiled
    _stats_mu  _stats, _bucket_stats, _warmup
    _device_mu device-exclusive sections (no container)
    ========== =========================================
    """
    pre = "%s." % getattr(engine, "name", "engine")
    engine._cache_mu = CheckedLock(pre + "_cache_mu")
    engine._device_mu = CheckedLock(pre + "_device_mu")
    engine._stats_mu = CheckedLock(pre + "_stats_mu")
    engine._cache = guard(engine._cache, engine._cache_mu, "_cache")
    engine._direct_cache = guard(engine._direct_cache, engine._cache_mu,
                                 "_direct_cache")
    engine._compiled = guard(engine._compiled, engine._cache_mu, "_compiled")
    engine._stats = guard(engine._stats, engine._stats_mu, "_stats")
    engine._bucket_stats = guard(engine._bucket_stats, engine._stats_mu,
                                 "_bucket_stats")
    # last: the subclass swap must not flag the guard() assignments above
    instrument_fields(engine, {"_warmup": "_stats_mu"})
    return engine
