"""JAX-hazard source lint (ISSUE 8, layer 2) — AST checks over the codebase.

The graph analyzers check plans; this module checks the *source that builds
them*.  The bug class is host/trace confusion: code that runs fine eagerly
but, inside a jitted function, either crashes at trace time, silently
constant-folds a value that should be traced, or forces a device sync per
step.  Every rule anchors to a hazard this repo has actually paid for
(PERF_NOTES' host-sync hunts, the PR 6 donation/cache-key corruption).

**Traced-region detection** — a function is considered traced when any of:

* decorated ``@jax.jit`` / ``@pjit`` / ``@partial(jax.jit, ...)``;
* decorated ``@register("Op")`` (the op registry: every registered op body
  is traced by ``Executor._graph_fn``);
* its name is passed to a trace consumer anywhere in the module
  (``jax.jit(fn)``, ``jax.vjp(f, ...)``, ``lax.scan(step, ...)``,
  ``pl.pallas_call(kernel, ...)``, ``jax.eval_shape``, ``vmap``/``grad``/
  ``remat``/``cond``/``while_loop``/``fori_loop``/``shard_map`` ...);
* it is nested inside a traced function (closures a jitted fn calls);
* its ``def`` line carries a ``# mxlint: traced`` marker (for functions
  handed to a tracer from another module, e.g. ``Executor._graph_fn``'s
  inner ``fn``).

This is a *heuristic* (module-local name resolution, no data flow), so every
rule is suppressible: a trailing ``# mxlint: ignore[code]`` comment kills
one line, and the committed baseline (``ci/mxlint_baseline.txt``) carries
the justified legacy sites — existing findings are suppressed *explicitly*,
never silently (the TVM/Relay discipline of PAPERS.md applied to lint).

Rules
-----
``bare-except``             ``except:`` swallows KeyboardInterrupt/SystemExit
                            and every bug (anywhere, not just traced code).
``np-in-traced``            ``np.*(...)`` call inside traced code whose
                            arguments reference a traced (positional)
                            parameter: numpy executes at trace time on the
                            host — a sync or TracerError.  Host math on
                            *statics* (shapes, attrs: ``np.ceil(h/stride)``)
                            is idiomatic and exempt, as are ``np.float32`` /
                            ``np.pi`` attribute reads and params reached
                            only through ``.shape``/``.ndim``/``.dtype``/
                            ``.size``/``len()`` (static under trace).
``scalar-coerce-in-traced`` ``float(x)`` / ``int(x)`` / ``bool(x)`` on a
                            traced parameter (same static exemptions), or
                            ``.item()`` / ``.tolist()`` / ``.asnumpy()``
                            anywhere in traced code — a concretization
                            error or a blocking device round-trip.
``branch-on-traced-param``  ``if``/``while`` whose test reads a *positional*
                            parameter of a traced function by bare name —
                            Python control flow on a tracer (the repo
                            convention keeps static attrs keyword-only, so
                            positional params are the traced values).  ``is
                            None`` checks are static and exempt.
``time-in-traced``          ``time.*()`` inside traced code: evaluates once
                            at trace time and bakes the timestamp into the
                            executable.
``donated-jit-unkeyed``     ``jax.jit(..., donate_argnums=...)`` in a scope
                            that never mentions ``compile_cache`` /
                            ``CachedFunction``: a donated executable the
                            AOT cache layer cannot see — exactly the shape
                            of the PR 6 XLA:CPU donated-restore corruption
                            (an unwired donated jit has no key carrying its
                            donation layout, so nothing can invalidate it).
``mixed-dtype-literal``     a Python float literal combined with a traced
                            parameter where the literal is NOT exactly
                            representable in bfloat16 (ISSUE 11): under
                            jax's weak typing the op computes in the
                            array's dtype, so a bf16 twin silently rounds
                            the constant the author wrote (``x + 1e-5`` is
                            the identity in bf16).  Exact literals (0.5,
                            2.0, 127.0 ...) are exempt — hoist the rest
                            into an explicit fp32 constant or a static
                            attr, or justify with an ignore.
``implicit-downcast``       ``.astype(...)``/``.view(...)`` to a narrow
                            dtype (bfloat16/float16/float8*/int8/uint8)
                            inside traced code with no ``# mxlint:
                            ignore[implicit-downcast]`` justification:
                            precision is dropped mid-graph where the
                            numerics analyzer can see it but a reviewer
                            cannot — every deliberate narrowing must carry
                            its reasoning (ISSUE 11; quantization op
                            bodies are the baselined legitimate sites).
"""
from __future__ import annotations

import ast
import os
import re
import struct

from .diagnostics import Diagnostic, WARNING

__all__ = ["LintFinding", "lint_source", "lint_paths", "load_baseline",
           "split_baseline", "format_baseline_line", "RULES"]

RULES = ("bare-except", "np-in-traced", "scalar-coerce-in-traced",
         "branch-on-traced-param", "time-in-traced", "donated-jit-unkeyed",
         "mixed-dtype-literal", "implicit-downcast")

# callables whose function-valued arguments get traced
_TRACE_CONSUMERS = frozenset({
    "jit", "pjit", "vjp", "jvp", "grad", "value_and_grad", "vmap", "pmap",
    "remat", "checkpoint", "eval_shape", "pallas_call", "scan",
    "while_loop", "fori_loop", "cond", "switch", "shard_map",
    "custom_vjp", "custom_jvp", "linear_transpose", "associative_scan",
})
# callables whose function-valued arguments run on the HOST by contract —
# a def handed to one of these is a host region even when nested inside
# traced code (jax.pure_callback bodies are the custom-op escape hatch)
_HOST_CONSUMERS = frozenset({"pure_callback", "io_callback", "callback"})
_JIT_NAMES = frozenset({"jit", "pjit"})
_COERCERS = frozenset({"float", "int", "bool", "complex"})
_SYNC_METHODS = frozenset({"item", "tolist", "asnumpy"})
# np.* helpers that only read metadata (delegate to .ndim/.shape/dtype
# protocols) — never convert, so safe on a tracer
_NP_META = frozenset({"ndim", "shape", "size", "dtype", "result_type",
                      "promote_types", "broadcast_shapes", "iinfo", "finfo"})

_IGNORE_RE = re.compile(r"#\s*mxlint:\s*ignore(?:\[([a-z0-9_,\- ]+)\])?")
_TRACED_RE = re.compile(r"#\s*mxlint:\s*traced\b")

# narrow-dtype tokens the implicit-downcast rule recognizes as targets of
# .astype()/.view() — 16 bits or fewer of float, or sub-f32 integer quant
_NARROW_DTYPES = frozenset({
    "bfloat16", "float16", "half", "int8", "uint8",
    "float8_e4m3fn", "float8_e5m2", "float8_e4m3", "float8_e5m2fnuz",
    "float8_e4m3fnuz",
})


def _bf16_exact(value):
    """Is a Python float exactly representable in bfloat16?  bf16 values
    are precisely the float32 values whose low 16 mantissa bits are zero,
    so: exact in f32 AND truncatable without loss."""
    try:
        as_f32 = struct.unpack("<f", struct.pack("<f", value))[0]
    except (OverflowError, struct.error):
        return False
    if as_f32 != value:
        return False
    bits = struct.unpack("<I", struct.pack("<f", value))[0]
    return (bits & 0xFFFF) == 0


def _dtype_token(arg):
    """The dtype a ``.astype(X)``/``.view(X)`` call names, as a bare token
    (``jnp.bfloat16`` -> ``bfloat16``, ``"float16"`` -> ``float16``), or
    None when the argument is dynamic (a variable — not statically
    narrow)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value.strip().lower()
    if isinstance(arg, ast.Attribute):
        return arg.attr
    if isinstance(arg, ast.Name):
        return arg.id
    return None


class LintFinding(Diagnostic):
    """A source-lint Diagnostic anchored to a file location, carrying the
    stable fingerprint the baseline mechanism keys on (path + enclosing
    qualname + rule + normalized source line — line NUMBERS are excluded on
    purpose, so unrelated edits above a justified site don't churn the
    baseline)."""

    __slots__ = ("path", "line", "col", "fingerprint", "_qualname")

    def __init__(self, code, severity, message, path, line, col, qualname):
        super().__init__(code, severity, message,
                         where="%s:%d" % (path, line), analyzer="source_lint")
        self.path = path
        self.line = line
        self.col = col
        self.fingerprint = None  # filled by lint_source after dedup
        self._qualname = qualname  # fingerprint component

    def __str__(self):
        return "%s:%d:%d: %s [%s] %s" % (self.path, self.line, self.col + 1,
                                         self.severity, self.code,
                                         self.message)


def _root_name(expr):
    """Terminal base Name of a Name/Attribute chain (``np.linalg.inv`` ->
    ``np``), or None."""
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _call_name(func):
    """The identifier a call is made through (``jax.jit`` -> ``jit``)."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_jitlike(expr):
    return _call_name(expr) in _JIT_NAMES and isinstance(
        expr, (ast.Name, ast.Attribute))


class _Linter:
    def __init__(self, tree, lines, path):
        self.tree = tree
        self.lines = lines
        self.path = path
        self.findings = []
        self.np_aliases = set()
        self.time_aliases = set()
        self.traced_seeds = set()   # names handed to a trace consumer
        self.host_seeds = set()     # names handed to a host callback
        self._collect_module_facts()

    # -- pass 1: imports + names that flow into tracers ----------------------
    def _collect_module_facts(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in ("numpy", "numpy.ma"):
                        self.np_aliases.add(a.asname or "numpy")
                    elif a.name == "time":
                        self.time_aliases.add(a.asname or "time")
            elif isinstance(node, ast.Call):
                cname = _call_name(node.func)
                seeds = (self.traced_seeds
                         if cname in _TRACE_CONSUMERS else
                         self.host_seeds if cname in _HOST_CONSUMERS
                         else None)
                if seeds is not None:
                    for arg in node.args:
                        if isinstance(arg, ast.Name):
                            seeds.add(arg.id)
                        elif isinstance(arg, ast.Attribute):
                            seeds.add(arg.attr)

    # -- suppression ---------------------------------------------------------
    def _suppressed(self, line_no, code):
        try:
            text = self.lines[line_no - 1]
        except IndexError:
            return False
        m = _IGNORE_RE.search(text)
        if not m:
            return False
        codes = m.group(1)
        if codes is None:
            return True
        return code in {c.strip() for c in codes.split(",")}

    def _emit(self, code, node, message, qualname):
        line = getattr(node, "lineno", 1)
        # a multi-line construct (e.g. a jit call spanning lines) accepts
        # the ignore comment on ANY of its physical lines — trailing
        # comments naturally land on the closing-paren line
        end = getattr(node, "end_lineno", None) or line
        if any(self._suppressed(ln, code) for ln in range(line, end + 1)):
            return
        self.findings.append(LintFinding(
            code, WARNING, message, self.path, line,
            getattr(node, "col_offset", 0), qualname))

    # -- traced-ness ---------------------------------------------------------
    def _def_is_traced(self, fd):
        for dec in fd.decorator_list:
            if _is_jitlike(dec):
                return True
            if isinstance(dec, ast.Call):
                cname = _call_name(dec.func)
                if _is_jitlike(dec.func):
                    return True
                if cname == "partial" and dec.args \
                        and _is_jitlike(dec.args[0]):
                    return True
                if cname in ("register", "register_op"):
                    return True  # op registry: body runs under _graph_fn
        if fd.name in self.traced_seeds:
            return True
        try:
            return bool(_TRACED_RE.search(self.lines[fd.lineno - 1]))
        except IndexError:
            return False

    # -- pass 2: walk with (qualname, traced, positional params) context -----
    def run(self):
        self._walk(self.tree.body, "<module>", False, frozenset())
        self._check_module_donated_jits()
        return self.findings

    def _walk(self, body, qual, traced, params, scope_seg=None):
        for node in body:
            self._visit(node, qual, traced, params, scope_seg)

    def _visit(self, node, qual, traced, params, scope_seg=None):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a def handed to a host callback is host code even inside a
            # traced region (custom-op pure_callback bodies)
            if node.name in self.host_seeds:
                sub_traced = False
            else:
                sub_traced = traced or self._def_is_traced(node)
            sub_qual = node.name if qual == "<module>" \
                else "%s.%s" % (qual, node.name)
            pos = [a.arg for a in node.args.posonlyargs + node.args.args
                   if a.arg not in ("self", "cls")]
            # the donation rule's "is the key wired?" scope: the nearest
            # TOP-LEVEL enclosing def's source (covers all nested lines,
            # so outer-scope CachedFunction wiring suppresses inner defs)
            seg = scope_seg if scope_seg is not None else "\n".join(
                self.lines[node.lineno - 1:node.end_lineno])
            self._walk(node.body, sub_qual, sub_traced, frozenset(pos), seg)
            for dec in node.decorator_list:
                self._scan_expr(dec, qual, traced, params)
            self._check_donated_jit_in(node, sub_qual, seg)
            return
        if isinstance(node, ast.ClassDef):
            self._walk(node.body, "%s.%s" % (qual, node.name)
                       if qual != "<module>" else node.name, traced, params,
                       scope_seg)
            return
        if isinstance(node, ast.ExceptHandler):
            if node.type is None:
                self._emit(
                    "bare-except", node,
                    "bare 'except:' swallows KeyboardInterrupt/SystemExit "
                    "and every bug — catch Exception (or narrower)", qual)
            self._walk(node.body, qual, traced, params, scope_seg)
            return
        if traced and isinstance(node, (ast.If, ast.While)):
            offender = self._traced_name_in_test(node.test, params)
            if offender:
                self._emit(
                    "branch-on-traced-param", node,
                    "%s on traced parameter %r — Python control flow "
                    "cannot see a tracer's value (use lax.cond/jnp.where, "
                    "or make the argument a static keyword-only attr)"
                    % ("if" if isinstance(node, ast.If) else "while",
                       offender), qual)
        # expressions anywhere inside this statement
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.ExceptHandler)):
                self._visit(child, qual, traced, params, scope_seg)
            elif isinstance(child, ast.expr):
                self._scan_expr(child, qual, traced, params)
            else:
                self._visit(child, qual, traced, params, scope_seg)

    def _traced_name_in_test(self, test, params):
        """First positional-param bare Name the test's truthiness depends
        on, or None.  ``x is None`` / ``x is not None`` comparisons are
        static under trace and exempt."""
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return None
        if isinstance(test, ast.BoolOp):
            for v in test.values:
                hit = self._traced_name_in_test(v, params)
                if hit:
                    return hit
            return None
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._traced_name_in_test(test.operand, params)
        if isinstance(test, ast.Compare):
            for operand in [test.left] + list(test.comparators):
                if isinstance(operand, ast.Name) and operand.id in params:
                    return operand.id
            return None
        if isinstance(test, ast.Name) and test.id in params:
            return test.id
        return None

    @staticmethod
    def _refs_traced_param(exprs, params):
        """Does any of ``exprs`` read a positional (traced) param by value?
        Reads reaching the param only through static accessors —
        ``x.shape`` / ``x.ndim`` / ``x.dtype`` / ``x.size`` / ``len(x)`` —
        are static under trace and don't count.  First offending name or
        None.  (No dataflow: a traced value laundered through a local is
        missed — precision over recall; the baseline covers what slips.)"""
        static_attrs = {"shape", "ndim", "dtype", "size"}
        exempt = set()
        names = []
        for e in exprs:
            for node in ast.walk(e):
                if isinstance(node, ast.Attribute) \
                        and node.attr in static_attrs \
                        and isinstance(node.value, ast.Name):
                    exempt.add(id(node.value))
                elif isinstance(node, ast.Call) \
                        and _call_name(node.func) == "len":
                    for a in node.args:
                        if isinstance(a, ast.Name):
                            exempt.add(id(a))
                elif isinstance(node, ast.Name) and node.id in params:
                    names.append(node)
        for n in names:
            if id(n) not in exempt:
                return n.id
        return None

    def _float_literal_of(self, operand):
        """The float value of a literal BinOp operand (unary minus
        unwrapped), or None when the operand is not a float literal."""
        if isinstance(operand, ast.UnaryOp) \
                and isinstance(operand.op, (ast.USub, ast.UAdd)):
            operand = operand.operand
        if isinstance(operand, ast.Constant) \
                and type(operand.value) is float:
            return operand.value
        return None

    def _check_mixed_literal(self, node, qual, params):
        """mixed-dtype-literal: a non-bf16-exact float literal as a direct
        BinOp operand against an expression reading a traced param —
        checked per BinOp so nested arithmetic attributes each literal to
        its own operation."""
        for lit_side, other in ((node.left, node.right),
                                (node.right, node.left)):
            v = self._float_literal_of(lit_side)
            if v is None or _bf16_exact(v):
                continue
            hit = self._refs_traced_param([other], params)
            if hit:
                self._emit(
                    "mixed-dtype-literal", node,
                    "float literal %r combines with traced parameter %r "
                    "but is not exactly representable in bfloat16 — a "
                    "bf16 twin silently rounds it (1 + 1e-5 IS 1 in "
                    "bf16); hoist it into an explicit fp32 constant, a "
                    "static attr, or justify with an ignore" % (v, hit),
                    qual)
                return  # one finding per BinOp is enough

    def _scan_expr(self, expr, qual, traced, params):
        if not traced:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.BinOp):
                self._check_mixed_literal(node, qual, params)
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            cname = _call_name(func)
            root = _root_name(func) if isinstance(func, ast.Attribute) \
                else None
            args = list(node.args) + [kw.value for kw in node.keywords]
            if root in self.np_aliases and cname not in _NP_META:
                hit = self._refs_traced_param(args, params)
                if hit:
                    self._emit(
                        "np-in-traced", node,
                        "numpy call '%s.%s(...)' on traced parameter %r "
                        "runs on the host at trace time — a device sync or "
                        "TracerError (use jnp, or hoist out of the traced "
                        "function)" % (root, cname, hit), qual)
            elif root in self.time_aliases:
                self._emit(
                    "time-in-traced", node,
                    "'%s.%s()' inside traced code evaluates ONCE at trace "
                    "time — the executable replays a frozen timestamp"
                    % (root, cname), qual)
            elif isinstance(func, ast.Attribute) \
                    and cname in ("astype", "view") and node.args:
                token = _dtype_token(node.args[0])
                if token in _NARROW_DTYPES:
                    self._emit(
                        "implicit-downcast", node,
                        ".%s(%s) narrows precision inside traced code — "
                        "deliberate quantization/bf16 sites must say why "
                        "(# mxlint: ignore[implicit-downcast] with a "
                        "reason, or a baselined justification); anything "
                        "else belongs to the future cast pass, not inline "
                        "code" % (cname, token), qual)
            elif isinstance(func, ast.Attribute) and cname in _SYNC_METHODS:
                self._emit(
                    "scalar-coerce-in-traced", node,
                    ".%s() inside traced code is a concretization error on "
                    "a tracer (and a blocking device round-trip on an "
                    "array)" % cname, qual)
            elif isinstance(func, ast.Name) and cname in _COERCERS \
                    and node.args:
                hit = self._refs_traced_param(node.args, params)
                if hit:
                    self._emit(
                        "scalar-coerce-in-traced", node,
                        "%s(...) on traced parameter %r concretizes the "
                        "value — TracerError under jit" % (cname, hit),
                        qual)

    @staticmethod
    def _walk_shallow(root):
        """``ast.walk`` that does NOT descend into nested function defs —
        each def's body belongs to that def's own visit."""
        stack = list(ast.iter_child_nodes(root))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    _DONATED_MSG = (
        "jax.jit(donate_argnums=...) with no compile_cache/"
        "CachedFunction wiring in scope: the donated executable "
        "carries no cache key reflecting its donation layout "
        "(the PR 6 donated-restore corruption shape) — wrap it "
        "in compile_cache.CachedFunction or baseline with a "
        "justification")

    def _check_donated_jit_in(self, fd, qual, seg):
        """Donation rule — each jit call is attributed to its INNERMOST
        enclosing def exactly once (the shallow walk leaves nested defs to
        their own visit); ``seg`` is the nearest top-level enclosing def's
        source, so the 'is the key wired?' question sees outer-scope
        wrapping too."""
        keyed = "compile_cache" in seg or "CachedFunction" in seg
        if keyed:
            return
        for node in self._walk_shallow(fd):
            if isinstance(node, ast.Call) and _is_jitlike(node.func) \
                    and any(kw.arg == "donate_argnums"
                            for kw in node.keywords):
                self._emit("donated-jit-unkeyed", node, self._DONATED_MSG,
                           qual)

    def _check_module_donated_jits(self):
        """Module/class-scope donated jits (``run = jax.jit(step,
        donate_argnums=(0,))`` at import time) — the PR 6 shape outside any
        def.  Module scope IS the file, so wiring anywhere in it counts as
        keyed."""
        src = "\n".join(self.lines)
        if "compile_cache" in src or "CachedFunction" in src:
            return
        for node in self._walk_shallow(self.tree):
            if isinstance(node, ast.Call) and _is_jitlike(node.func) \
                    and any(kw.arg == "donate_argnums"
                            for kw in node.keywords):
                self._emit("donated-jit-unkeyed", node, self._DONATED_MSG,
                           "<module>")


def _fingerprint(findings):
    """Fill ``fingerprint`` on every finding: path::qualname::rule::
    normalized-source-line, de-duplicated with a ::N occurrence suffix.
    Line-number free, so edits elsewhere in the file don't invalidate a
    committed baseline entry."""
    seen = {}
    for f in findings:
        base = "%s::%s::%s" % (f.path, f._qualname, f.code)
        n = seen.get(base, 0)
        seen[base] = n + 1
        f.fingerprint = base + ("::%d" % n if n else "")
    return findings


def lint_source(src, path="<string>", lines=None):
    """Lint one module's source -> [LintFinding] in file order (with
    fingerprints filled).  ``path`` is the fingerprint/display path."""
    tree = ast.parse(src, filename=path)
    if lines is None:
        lines = src.splitlines()
    linter = _Linter(tree, lines, path)
    findings = linter.run()
    findings.sort(key=lambda f: (f.line, f.col, f.code))
    # normalized source line enters the fingerprint here (linter kept the
    # lines around): whitespace-collapsed, so reformatting alone is stable
    for f in findings:
        f._qualname = "%s@%s" % (
            f._qualname,
            re.sub(r"\s+", " ", lines[f.line - 1].strip())
            if 0 < f.line <= len(lines) else "")
    return _fingerprint(findings)


def lint_paths(paths, root=None):
    """Lint every ``*.py`` under ``paths`` (files or directories) ->
    [LintFinding].  Fingerprint paths are made relative to ``root`` (posix
    separators) so baselines are machine-independent."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__" and
                               not d.startswith(".")]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames) if f.endswith(".py"))
        else:
            files.append(p)
    out = []
    for fp in sorted(files):
        rel = os.path.relpath(fp, root) if root else fp
        rel = rel.replace(os.sep, "/")
        try:
            with open(fp, "r", encoding="utf-8") as fh:
                src = fh.read()
        except (OSError, UnicodeDecodeError):
            continue
        try:
            out.extend(lint_source(src, path=rel))
        except SyntaxError as e:
            out.extend(_fingerprint([LintFinding(
                "syntax-error", WARNING,
                "file does not parse (%s); lint skipped" % e,
                rel, 1, 0, "<module>@")]))
    return out


# -- baseline ----------------------------------------------------------------

def format_baseline_line(finding, justification=""):
    just = "  # %s" % justification if justification else ""
    return finding.fingerprint + just


def load_baseline(path):
    """Baseline file -> set of fingerprints.  One fingerprint per line;
    ``#``-to-EOL is a justification comment; blank lines ignored."""
    fps = set()
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.split("  #", 1)[0].strip()
                if line and not line.startswith("#"):
                    fps.add(line)
    except OSError:
        pass
    return fps


def split_baseline(findings, baseline):
    """-> (new, suppressed, stale): findings not in / in the baseline, and
    baseline fingerprints matching nothing (candidates for deletion —
    reported, never auto-pruned)."""
    new = [f for f in findings if f.fingerprint not in baseline]
    suppressed = [f for f in findings if f.fingerprint in baseline]
    live = {f.fingerprint for f in findings}
    stale = sorted(baseline - live)
    return new, suppressed, stale
