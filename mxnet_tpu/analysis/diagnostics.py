"""Diagnostic — the one result type every analysis layer emits (ISSUE 8).

Graph-IR analyzers, the source lint, and the lock-discipline checker all
report through this shape so CLIs, warmup report rows, and tests can treat
"a finding" uniformly.  A Diagnostic is a value, never an exception: the
caller decides whether a given severity warrants failing (``tools/mxlint.py``
exits nonzero on new findings; ``Executor.check`` just returns the list).
"""
from __future__ import annotations

__all__ = ["Diagnostic", "ERROR", "WARNING", "INFO", "worst_severity"]

# severity ladder, most severe first — ordering is part of the contract
# (worst_severity / sort keys rely on it)
ERROR = "error"
WARNING = "warning"
INFO = "info"
_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}


class Diagnostic:
    """One finding.

    ``code``      stable kebab-case rule id ("prng-shared-stream", ...);
    ``severity``  "error" | "warning" | "info";
    ``message``   human sentence with the specifics;
    ``where``     what it anchors to — node/field/file:line, or None;
    ``analyzer``  the registered analyzer (or lint rule source) that
                  produced it, filled in by the manager.
    """

    __slots__ = ("code", "severity", "message", "where", "analyzer")

    def __init__(self, code, severity, message, where=None, analyzer=None):
        if severity not in _ORDER:
            raise ValueError("severity %r not in %s"
                             % (severity, tuple(_ORDER)))
        self.code = str(code)
        self.severity = severity
        self.message = str(message)
        self.where = where
        self.analyzer = analyzer

    def _sort_key(self):
        return (_ORDER[self.severity], self.code, str(self.where))

    def __repr__(self):
        return "Diagnostic(%s, %s, %r)" % (self.code, self.severity,
                                           self.message)

    def __str__(self):
        loc = " [%s]" % (self.where,) if self.where else ""
        return "[%s] %s%s: %s" % (self.severity, self.code, loc, self.message)


def worst_severity(diagnostics):
    """The most severe level present, or None for an empty list."""
    worst = None
    for d in diagnostics:
        if worst is None or _ORDER[d.severity] < _ORDER[worst]:
            worst = d.severity
    return worst
