"""Device context — TPU-native equivalent of reference ``python/mxnet/context.py``.

In the reference a ``Context(dev_type, dev_id)`` names a CPU/GPU device and a
thread-local default-context stack scopes imperative ops onto it.  Here a
Context maps onto a concrete ``jax.Device``.  ``gpu(i)`` is kept as an alias
for the i-th accelerator so reference scripts run unchanged on TPU.
"""
from __future__ import annotations

import threading

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus"]


class Context:
    """A device context.

    Parameters mirror the reference (``python/mxnet/context.py:23``):
    ``Context('tpu', 0)``, ``Context('cpu')``.  ``device_type`` of ``'gpu'``
    resolves to the platform's accelerators (TPU here) so that reference
    training scripts written with ``mx.gpu(i)`` work verbatim.
    """

    _default_ctx = threading.local()

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __repr__(self):
        return self.__str__()

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # -- JAX mapping --------------------------------------------------------
    @property
    def jax_device(self):
        """The concrete ``jax.Device`` this context denotes."""
        import jax

        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            return jax.devices("cpu")[self.device_id]
        # 'gpu' and 'tpu' both mean "the platform accelerator".
        accel = _accelerator_devices()
        if not accel:
            return jax.devices()[min(self.device_id, len(jax.devices()) - 1)]
        return accel[self.device_id % len(accel)]

    def empty_cache(self):
        """Release pooled device memory (reference ctx.empty_cache)."""
        # XLA owns the allocator; live buffers are freed by GC.  Nothing to do
        # beyond encouraging a collection.
        import gc

        gc.collect()


def _accelerator_devices():
    import jax

    try:
        devs = jax.devices()
    except RuntimeError:
        return []
    return [d for d in devs if d.platform != "cpu"] or devs


def cpu(device_id=0):
    """Return a CPU context."""
    return Context("cpu", device_id)


def gpu(device_id=0):
    """Return the i-th accelerator context (alias of :func:`tpu` on TPU hosts)."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """Return a TPU context."""
    return Context("tpu", device_id)


def num_gpus():
    """Number of accelerator devices visible (reference mx.context.num_gpus)."""
    import jax

    try:
        return len([d for d in jax.devices() if d.platform != "cpu"])
    except RuntimeError:
        return 0


num_tpus = num_gpus


def current_context():
    """The thread-local default context (reference context.py current_context)."""
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value
