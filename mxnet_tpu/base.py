"""Core shared plumbing: dtypes, errors, name management, attribute parsing.

TPU-native re-imagining of the reference's ``python/mxnet/base.py`` (ctypes
loading, handle types, error checking — see reference base.py:532 op-module
codegen driver).  There is no C ABI here: the "backend" is JAX/XLA, so this
module only keeps the pieces that are about *semantics* (dtype tables, error
types, name managers, string-attr parsing for Symbol JSON compatibility).
"""
from __future__ import annotations

import re
import threading

import numpy as np

__all__ = [
    "MXNetError",
    "DTYPE_NAMES",
    "NAME_TO_DTYPE",
    "string_types",
    "numeric_types",
    "integer_types",
    "NameManager",
    "AttrScope",
    "env_flag",
]

# the one shared falsy-string list for boolean MXNET_* env gates
# (MXNET_TELEMETRY, MXNET_MODULE_FUSED_STEP, ...): extending it here keeps
# every gate agreeing on what counts as "off"
_ENV_FALSY = ("", "0", "false", "no", "off")


def env_flag(name, default="0"):
    """Boolean env gate: False for unset->default in {'', 0, false, no, off}
    (case/whitespace-insensitive), True otherwise.  Read per call so tests
    can flip it; one dict lookup, cheap enough for per-batch guards."""
    import os

    return os.environ.get(name, default).strip().lower() not in _ENV_FALSY


class MXNetError(RuntimeError):
    """Framework error type (mirrors reference MXNetError semantics)."""


string_types = (str,)
numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)

# dtype universe — mirrors reference mshadow dtype enum plus TPU-first bfloat16.
# (reference: include/mxnet/tensor_blob.h dtype switch; python base.py _DTYPE_NP_TO_MX)
DTYPE_NAMES = (
    "float32",
    "float64",
    "float16",
    "bfloat16",
    "uint8",
    "int32",
    "int8",
    "int64",
    "bool",
)


def _np_dtype(name):
    if name == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return np.dtype(name)


NAME_TO_DTYPE = {n: n for n in DTYPE_NAMES}


def dtype_np(dtype):
    """Normalize a user-provided dtype (str | np.dtype | jnp dtype) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        return _np_dtype(dtype)
    return np.dtype(dtype) if not _is_bf16(dtype) else dtype


def _is_bf16(dtype):
    return getattr(dtype, "__name__", None) == "bfloat16" or str(dtype) == "bfloat16"


def dtype_name(dtype):
    """Canonical string name for a dtype."""
    if isinstance(dtype, str):
        return dtype
    if _is_bf16(dtype):
        return "bfloat16"
    return np.dtype(dtype).name


class NameManager:
    """Automatic unique-name assignment for symbols/blocks.

    Mirrors the reference ``python/mxnet/name.py`` NameManager (thread-local
    current stack).
    """

    _current = threading.local()

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        if not hasattr(NameManager._current, "value"):
            NameManager._current.value = NameManager()
        self._old_manager = NameManager._current.value
        NameManager._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        NameManager._current.value = self._old_manager

    @staticmethod
    def current():
        if not hasattr(NameManager._current, "value"):
            NameManager._current.value = NameManager()
        return NameManager._current.value


class Prefix(NameManager):
    """NameManager that attaches a prefix to all names."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name


class AttrScope:
    """Attribute manager for symbol attrs (``with AttrScope(ctx_group='dev1')``).

    Mirrors reference ``python/mxnet/attribute.py``; the ``__ctx_group__`` attr
    feeds sharding annotation the way group2ctx fed PlaceDevice
    (reference src/executor/graph_executor.cc:407).
    """

    _current = threading.local()

    def __init__(self, **kwargs):
        self._old_scope = None
        for value in kwargs.values():
            if not isinstance(value, string_types):
                raise ValueError("Attributes need to be a string")
        self._attr = kwargs

    def get(self, attr):
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        self._old_scope = AttrScope._current.value
        attr = AttrScope._current.value._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        AttrScope._current.value = self._old_scope

    @staticmethod
    def current():
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        return AttrScope._current.value


# ---------------------------------------------------------------------------
# String-attr parsing: ops accept kwargs either as native Python values or as
# strings (Symbol JSON round-trip compatibility with the reference's
# dmlc::Parameter string parsing — SURVEY §5.6 mechanism 2).
# ---------------------------------------------------------------------------

_TUPLE_RE = re.compile(r"^[\(\[].*[\)\]]$")


def parse_attr(value):
    """Parse a string attribute to a Python value (int/float/bool/tuple/str)."""
    if not isinstance(value, str):
        return value
    s = value.strip()
    low = s.lower()
    if low in ("true", "1") and low == "true":
        return True
    if low == "false":
        return False
    if low == "none":
        return None
    if _TUPLE_RE.match(s):
        inner = s[1:-1].strip()
        if not inner:
            return ()
        return tuple(parse_attr(tok) for tok in inner.split(","))
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s


def attr_str(value):
    """Serialize a Python attr value to its canonical string form."""
    if isinstance(value, bool):
        return str(value)
    if isinstance(value, (tuple, list)):
        return "(" + ", ".join(attr_str(v) for v in value) + ")"
    return str(value)
