"""Python custom operators — reference ``python/mxnet/operator.py``
(CustomOp :426, CustomOpProp :472, register :692; older NDArrayOp/NumpyOp
interfaces are intentionally dropped — CustomOp superseded them in the
reference too).

Usage (identical to the reference)::

    class Softmax(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            ...
            self.assign(out_data[0], req[0], mx.nd.array(y))
        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            ...

    @mx.operator.register("softmax")
    class SoftmaxProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=False)
        def list_arguments(self): return ['data', 'label']
        def list_outputs(self): return ['output']
        def infer_shape(self, in_shape): ...

    out = mx.nd.Custom(x, label, op_type='softmax')

Execution happens through ``jax.pure_callback`` (ops/custom.py), so the op
body may use arbitrary host code (numpy/cython) and still run inside jitted
graphs — the TPU-native answer to the reference's engine-async custom op
(src/operator/custom/custom.cc ExecType::kAsync).
"""
from __future__ import annotations

import numpy as np

from .ops import custom as _custom

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered_operators"]


class CustomOp:
    """Base class for custom operators (reference operator.py:426)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Assign src to dst honoring the write/add/null request
        (reference operator.py CustomOp.assign)."""
        if req == "null":
            return
        from .ndarray.ndarray import NDArray

        src_nd = src if isinstance(src, NDArray) else None
        if req in ("write", "inplace"):
            dst._rebind(src_nd._data if src_nd is not None else np.asarray(src))
        elif req == "add":
            dst._rebind(dst._data + (src_nd._data if src_nd is not None else np.asarray(src)))
        else:
            raise ValueError("unknown req %r" % req)


class CustomOpProp:
    """Operator properties: arity, shapes, types (reference operator.py:472)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        """Default: all outputs shaped like in_shape[0] (reference default)."""
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (
            in_type,
            [in_type[0]] * len(self.list_outputs()),
            [in_type[0]] * len(self.list_auxiliary_states()),
        )

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Class decorator registering a CustomOpProp under ``op_type=reg_name``
    (reference operator.py:692)."""

    def do_register(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise TypeError("register expects a CustomOpProp subclass")
        _custom.register_prop(reg_name, prop_cls)
        return prop_cls

    return do_register


def unregister(reg_name):
    """Remove a registered CustomOpProp (frees per-instance registrations)."""
    _custom.unregister_prop(reg_name)


def get_all_registered_operators():
    return list(_custom.PROP_REGISTRY)
