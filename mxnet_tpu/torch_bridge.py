"""Torch interop — the reference's torch plugin, TPU-native.

Reference counterparts:
- `python/mxnet/torch.py` (`mx.th.*`: torch tensor/math functions applied to
  NDArrays via the TorchModule plugin ABI),
- `plugin/torch/torch_module-inl.h` (`TorchModule` op: run a torch `nn`
  module inside the framework's graph, weights owned by the framework),
- `plugin/torch/torch_criterion-inl.h` (`TorchCriterion`: torch loss inside
  the graph).

Design: torch here is host-side (CPU build).  Pointwise/tensor functions are
wrapped NDArray→torch→NDArray (`function`, plus a generated `mx.th.*`
namespace, mirroring the reference's generated bindings).  `TorchModule` /
`TorchCriterion` embed a live ``torch.nn.Module`` as a gluon Block whose
parameters are framework-owned (updated by `Trainer`/KVStore like any other
Parameter) and whose forward/backward run through the CustomOp bridge
(`operator.py` → ``jax.pure_callback`` + ``custom_vjp``), with gradients
computed by torch autograd on the host.  This mirrors the reference exactly:
the plugin ran torch kernels on the framework's tensors inside the engine;
here the host callback is the "device" boundary instead of TH/THC.

Requires the baked-in CPU torch; import fails with a clear error otherwise.
"""
from __future__ import annotations

import numpy as np

try:
    import torch as _torch
except ImportError as _e:  # pragma: no cover
    raise ImportError(
        "mxnet_tpu.th requires the 'torch' package (the reference's torch "
        "plugin is optional too: MXNET_USE_TORCH)") from _e

from . import ndarray as nd
from .ndarray import NDArray
from . import operator as _op_mod

__all__ = ["to_torch", "from_torch", "function", "TorchModule",
           "TorchCriterion"]


def to_torch(x):
    """NDArray/numpy → host torch tensor (reference plugin's TBlob→THTensor)."""
    if isinstance(x, NDArray):
        x = x.asnumpy()
    # jax-exported numpy buffers are read-only; torch wants writable memory
    return _torch.from_numpy(np.array(x, order="C"))


def from_torch(t, ctx=None):
    """torch tensor → NDArray (device transfer happens lazily via jax)."""
    return nd.array(t.detach().cpu().numpy(), ctx=ctx)


def function(fn, name=None):
    """Wrap a torch callable to take/return NDArrays (reference torch.py's
    generated function wrappers).  Non-array args pass through."""

    def wrapped(*args, **kwargs):
        targs = [to_torch(a) if isinstance(a, (NDArray, np.ndarray)) else a
                 for a in args]
        tkw = {k: to_torch(v) if isinstance(v, (NDArray, np.ndarray)) else v
               for k, v in kwargs.items()}
        out = fn(*targs, **tkw)
        if isinstance(out, _torch.Tensor):
            return from_torch(out)
        if isinstance(out, (tuple, list)):
            return type(out)(from_torch(o) if isinstance(o, _torch.Tensor)
                             else o for o in out)
        return out

    wrapped.__name__ = name or getattr(fn, "__name__", "torch_fn")
    wrapped.__doc__ = "NDArray wrapper over torch.%s" % wrapped.__name__
    return wrapped


# generated namespace, mirroring the reference's auto-registered th.* ops
_TH_FUNCS = [
    "abs", "acos", "asin", "atan", "ceil", "cos", "cosh", "exp", "floor",
    "log", "log1p", "neg", "round", "rsqrt", "sigmoid", "sign", "sin",
    "sinh", "sqrt", "tan", "tanh", "trunc", "add", "sub", "mul", "div",
    "pow", "fmod", "remainder", "clamp", "maximum", "minimum", "mm",
    "matmul", "bmm", "dot", "cat", "stack", "squeeze", "unsqueeze", "sum",
    "mean", "std", "var", "norm", "cumsum", "cumprod", "sort", "topk",
]
for _f in _TH_FUNCS:
    if hasattr(_torch, _f):
        globals()[_f] = function(getattr(_torch, _f), _f)


def _flat_params(mod):
    out, seen = [], {}
    for n, p in mod.named_parameters():
        flat = n.replace(".", "_")
        if flat in seen:  # dot-mangling can collide ('a.b_w' vs 'a_b.w')
            seen[flat] += 1
            flat = "%s__%d" % (flat, seen[flat])
        else:
            seen[flat] = 0
        out.append((flat, p))
    return out


class _TorchOpProp(_op_mod.CustomOpProp):
    """CustomOpProp driving a torch module: args = [data..., params...]."""

    def __init__(self, tmod, n_data, criterion=False, input_dtypes=None,
                 shape_cache=None):
        super().__init__(need_top_grad=not criterion)
        self._tmod = tmod
        self._n_data = n_data
        self._criterion = criterion
        self._input_dtypes = input_dtypes
        # shared across prop instances (a fresh prop is built per execution):
        # the probe forward must run once per signature, not once per call
        self._shape_cache = {} if shape_cache is None else shape_cache

    def infer_type(self, in_type):
        # the bridge computes in torch float32 regardless of index-typed
        # inputs; without this, integer inputs would imply integer outputs
        # and truncate the module's float results
        return list(in_type), [np.dtype(np.float32)], []

    def list_arguments(self):
        data = ["data%d" % i for i in range(self._n_data)]
        return data + [n for n, _ in _flat_params(self._tmod)]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        # torch's own shape propagation, run ONCE per input signature on a
        # throwaway copy (never mutates the live module's buffers — e.g.
        # BatchNorm running stats — and never pays per-call host compute)
        key = tuple(tuple(s) for s in in_shape[:self._n_data])
        if key not in self._shape_cache:
            import copy
            probe = copy.deepcopy(self._tmod).eval()
            dts = self._input_dtypes or [None] * self._n_data
            with _torch.no_grad():
                try:
                    outs = probe(*[_torch.zeros(s, dtype=dt)
                                   for s, dt in zip(key, dts)])
                except (RuntimeError, TypeError):
                    # integer-input modules (Embedding etc.)
                    outs = probe(*[_torch.zeros(s, dtype=_torch.long)
                                   for s in key])
            out = outs[0] if isinstance(outs, (tuple, list)) else outs
            self._shape_cache[key] = tuple(out.shape)
        return in_shape, [self._shape_cache[key]], []

    def create_operator(self, ctx, shapes, dtypes):
        prop = self

        class _TorchOp(_op_mod.CustomOp):
            def _run(self, in_data, want_grad, is_train):
                n = prop._n_data
                dts = prop._input_dtypes or [None] * n
                xs = [to_torch(a).to(dt) if dt is not None
                      else to_torch(a).float()
                      for a, dt in zip(in_data[:n], dts)]
                plist = _flat_params(prop._tmod)
                with _torch.no_grad():
                    for (pname, p), arr in zip(plist, in_data[n:]):
                        p.copy_(to_torch(arr).float())
                # grad flags must be set BEFORE the forward builds the graph
                # (user-frozen torch params would otherwise silently drop out)
                for x in xs:
                    if x.is_floating_point():
                        x.requires_grad_(want_grad)
                for _, p in plist:
                    p.requires_grad_(want_grad)
                prop._tmod.train(bool(is_train))
                out = prop._tmod(*xs)
                if isinstance(out, (tuple, list)):
                    out = out[0]
                return xs, [p for _, p in plist], out

            def forward(self, is_train, req, in_data, out_data, aux):
                # stash the RNG state so backward's recompute replays the
                # SAME stochastic pass (dropout masks etc.)
                self._rng_state = _torch.get_rng_state()
                self._was_train = bool(is_train)
                # the vjp machinery may replay this forward several times;
                # keep it buffer-pure and let backward apply the one real
                # stateful update (BN running stats etc.)
                bufs = [(b, b.detach().clone())
                        for b in prop._tmod.buffers()] if is_train else []
                with _torch.no_grad():
                    _, _, out = self._run(in_data, want_grad=False,
                                          is_train=is_train)
                    for b, saved in bufs:
                        b.copy_(saved)
                self.assign(out_data[0], req[0], from_torch(out))

            def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                # recompute forward under torch autograd (reference plugin
                # called the module's own backward; CustomOp re-presents
                # in_data here, same contract), replaying forward's RNG
                if getattr(self, "_rng_state", None) is not None:
                    _torch.set_rng_state(self._rng_state)
                # this recompute applies the step's ONE stateful buffer
                # update (forward keeps buffers pure — it may be replayed)
                xs, ps, out = self._run(
                    in_data, want_grad=True,
                    is_train=getattr(self, "_was_train", True))
                head = (to_torch(out_grad[0]).float() if not prop._criterion
                        else _torch.ones_like(out))
                grads = _torch.autograd.grad(
                    out, [t for t in xs + ps if t.requires_grad],
                    grad_outputs=head, allow_unused=True)
                it = iter(grads)
                grads = [next(it) if t.requires_grad else None
                         for t in xs + ps]
                for i, g in enumerate(grads):
                    if g is None:
                        g = _torch.zeros_like((xs + ps)[i])
                    self.assign(in_grad[i], req[i], from_torch(g))

        return _TorchOp()


_INSTANCE_COUNT = [0]


def _register_prop(tmod, n_data, criterion, input_dtypes=None):
    # unique per wrapper instance: wrapping the same torch module twice (or
    # with different num_data) must not alias registrations
    _INSTANCE_COUNT[0] += 1
    key = "_torch_module_%d" % _INSTANCE_COUNT[0]
    shape_cache = {}  # class-level: survives per-execution prop instances

    @_op_mod.register(key)
    class _Prop(_TorchOpProp):
        def __init__(self):
            super().__init__(tmod, n_data, criterion, input_dtypes,
                             shape_cache=shape_cache)

    return key


class TorchModule:
    """Embed a ``torch.nn.Module`` in the framework (reference
    `plugin/torch/torch_module-inl.h`): parameters are framework NDArrays
    (initialized from the torch module's state, updatable by any Trainer /
    optimizer / KVStore path), execution is torch on host via the CustomOp
    bridge, gradients flow through `autograd.record()` like any op.

    Stateful-buffer contract: a training forward keeps torch buffers
    (BatchNorm running stats) PURE — the step's one buffer update is applied
    during the backward recompute.  A training forward whose output never
    receives a backward pass therefore skips that step's stat update (the
    reference plugin, which mutated buffers in forward, would have applied
    it).  Inference forwards never touch buffers in either design.
    """

    def __init__(self, torch_module, num_data=1, input_dtypes=None,
                 _criterion=False):
        self._tmod = torch_module.float()
        self._n_data = num_data
        self._criterion = _criterion
        if input_dtypes is not None:
            input_dtypes = [getattr(_torch, d) if isinstance(d, str) else d
                            for d in input_dtypes]
        self._key = _register_prop(self._tmod, num_data, _criterion,
                                   input_dtypes)
        # release the registry entry (and the captured torch module) when
        # this wrapper is garbage-collected
        import weakref
        self._finalizer = weakref.finalize(
            self, _op_mod.unregister, self._key)
        self._params = {n: from_torch(p) for n, p in _flat_params(self._tmod)}
        for p in self._params.values():
            p.attach_grad()

    def close(self):
        """Explicitly unregister (also runs automatically on GC)."""
        self._finalizer()

    @property
    def params(self):
        """name → NDArray (attach_grad'ed; pass to your optimizer)."""
        return self._params

    def __call__(self, *data):
        args = list(data) + [self._params[n]
                             for n, _ in _flat_params(self._tmod)]
        return nd.Custom(*args, op_type=self._key)


class TorchCriterion(TorchModule):
    """Torch loss inside the graph (reference torch_criterion-inl.h);
    ``need_top_grad=False`` — it is a terminal loss node."""

    def __init__(self, torch_loss, num_data=2):
        super().__init__(torch_loss, num_data, _criterion=True)
