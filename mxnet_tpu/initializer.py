"""Weight initializers — reference ``python/mxnet/initializer.py`` (registry
at :53; Uniform :442, Xavier :545, plus Normal/Orthogonal/MSRAPrelu/Bilinear/
LSTMBias/One/Zero/Constant/Mixed/Load).

Initializers fill NDArrays in place (functional rebind) using the global
seeded RNG, with the reference's name-based dispatch (``_weight``/``_bias``/
``_gamma``... suffixes) for the legacy ``__call__(name, arr)`` path.
"""
from __future__ import annotations

import json
import re

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array

__all__ = [
    "Initializer",
    "Uniform",
    "Normal",
    "Zero",
    "One",
    "Constant",
    "Orthogonal",
    "Xavier",
    "MSRAPrelu",
    "Bilinear",
    "LSTMBias",
    "Mixed",
    "Load",
    "InitDesc",
    "register",
]

_INIT_REGISTRY = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


_ALIASES = {"zeros": "zero", "ones": "one"}  # gluon-style names (reference accepts both)


def create(name, *args, **kwargs):
    if isinstance(name, Initializer):
        return name
    key = name.lower()
    key = _ALIASES.get(key, key)
    return _INIT_REGISTRY[key](*args, **kwargs)


class InitDesc(str):
    """Parameter name + attrs hint (reference initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer with name-based dispatch (reference initializer.py:53)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be str/InitDesc")
        if isinstance(desc, InitDesc) and desc.global_init is None:
            desc.global_init = self
        init = getattr(desc, "attrs", {}).get("__init__", "")
        if init:
            klass, kwargs = json.loads(init)
            create(klass, **kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- fill helpers --------------------------------------------------------
    def _fill(self, arr, np_values):
        arr._rebind(array(np_values.astype(np.float32) if np_values.dtype == np.float64 else np_values)._data.astype(arr._data.dtype))

    def _init_zero(self, _, arr):
        self._fill(arr, np.zeros(arr.shape, dtype=np.float32))

    def _init_one(self, _, arr):
        self._fill(arr, np.ones(arr.shape, dtype=np.float32))

    def _init_bias(self, _, arr):
        self._init_zero(_, arr)

    def _init_gamma(self, _, arr):
        self._init_one(_, arr)

    def _init_beta(self, _, arr):
        self._init_zero(_, arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override _init_weight")

    def _init_default(self, name, arr):
        raise ValueError(
            "Unknown initialization pattern for %s. Default init supports only weight/bias/gamma/beta; "
            "use mx.sym.Variable(init=...) for customization." % name
        )

    def _rng(self):
        from . import random as _rnd

        return np.random.RandomState(np.asarray(_rnd.next_key())[-1] % (2**31))


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        self._init_zero(_, arr)


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        self._init_one(_, arr)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        self._fill(arr, np.full(arr.shape, self.value, dtype=np.float32))


@register
class Uniform(Initializer):
    """U(-scale, scale) (reference initializer.py:442)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        self._fill(arr, self._rng().uniform(-self.scale, self.scale, arr.shape))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        self._fill(arr, self._rng().normal(0, self.sigma, arr.shape))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        rng = self._rng()
        if self.rand_type == "uniform":
            tmp = rng.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = rng.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else v
        self._fill(arr, (self.scale * res).reshape(arr.shape))


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference initializer.py:545)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier requires ndim >= 2: %s %s" % (name, shape))
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in, "out": fan_out}[self.factor_type]
        scale = np.sqrt(self.magnitude / factor)
        rng = self._rng()
        if self.rnd_type == "uniform":
            self._fill(arr, rng.uniform(-scale, scale, shape))
        elif self.rnd_type == "gaussian":
            self._fill(arr, rng.normal(0, scale, shape))
        else:
            raise ValueError("Unknown random type %s" % self.rnd_type)


@register
class MSRAPrelu(Xavier):
    """Kaiming-He init (reference initializer.py MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope**2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference initializer.py Bilinear)."""

    def _init_weight(self, _, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._fill(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        out = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = arr.shape[0] // 4
        out[num_hidden : 2 * num_hidden] = self.forget_bias
        self._fill(arr, out)


@register
class FusedRNN(Initializer):
    """Init for fused RNN packed params (reference initializer.py FusedRNN)."""

    def __init__(self, init=None, num_hidden=0, num_layers=0, mode="lstm", bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = create(klass, **kwargs)
        super().__init__(
            init=init.dumps() if init is not None else None,
            num_hidden=num_hidden,
            num_layers=num_layers,
            mode=mode,
            bidirectional=bidirectional,
            forget_bias=forget_bias,
        )
        self._init = init

    def _init_weight(self, desc, arr):
        if self._init is not None:
            self._init._init_weight(desc, arr)
        else:
            Uniform(0.07)._init_weight(desc, arr)


class Mixed:
    """Patterns → initializers (reference initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must have the same length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError("Parameter name %s did not match any pattern. Add a '.*' pattern as fallback." % name)


@register
class Load:
    """Init from a dict of arrays (reference initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as nd_load

            param = nd_load(param)
        self.param = {}
        for name, arr in param.items():
            self.param[name.replace("arg:", "").replace("aux:", "")] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            p = self.param[name]
            if tuple(p.shape) != tuple(arr.shape):
                raise ValueError("Parameter %s shape mismatch: %s vs %s" % (name, p.shape, arr.shape))
            arr._rebind(p._data if isinstance(p, NDArray) else array(p)._data)
        else:
            if self.default_init is None:
                raise ValueError("Cannot init %s: not found and no default_init" % name)
            self.default_init(name, arr)
