"""Shared model helpers + legacy FeedForward API.

Reference ``python/mxnet/model.py``: kvstore selection (`_create_kvstore:77`),
kvstore-driven update loops (`:116-157`), checkpoint save/load (`:384,414`).
"""
from __future__ import annotations

import logging

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "BatchEndParam",
    "FeedForward",
]

import collections

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"]
)


def _create_kvstore(kvstore, num_device, arg_params, mesh=None):
    """Resolve a kvstore spec → (kvstore, update_on_kvstore).

    Reference ``model.py:77``.  On TPU a single process drives all local
    devices and gradient reduction happens in-step via psum, so a store is
    only created for explicit instances or dist types.  ``mesh`` is the
    Module's device mesh: a local-family *string* spec (``'local'`` /
    ``'device'`` / ``'nccl'``) under a dp mesh resolves to no store at all —
    where the reference built a CommDevice reduction tree per key
    (``comm.h:451``), the sharded fused step's in-step psum (ISSUE 5,
    ``module/fused_step.py``) already sums gradients over the dp axis inside
    the compiled step, so an eager push/pull loop would only re-serialize
    it.  Dist specs still create real stores (cross-process aggregation has
    no in-step equivalent); explicit ``KVStore`` instances are honored and
    folded later via ``KVStore.folds_into_fused_step`` when possible.
    """
    from . import kvstore as kv_mod

    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kv_mod.KVStore):
        kv = kvstore
        if mesh is not None and kv.folds_into_fused_step(mesh):
            # explicit local-family store under a dp mesh: keep the store as
            # the (identity) grad-aggregation layer but let the local
            # updater own the optimizer, so the fused step can absorb the
            # whole update (stores running their own updater/optimizer or
            # compression keep update_on_kvstore=True and the legacy path)
            update_on_kvstore = False
    elif isinstance(kvstore, str):
        if "dist" not in kvstore and (num_device == 1 or mesh is not None):
            # single device, or single-process dp mesh: the local updater
            # plus the in-step psum is cheaper than a store round-trip
            kv = None
        else:
            kv = kv_mod.create(kvstore)
            if kvstore == "local":
                max_size = max(int(__import__("numpy").prod(p.shape)) for p in arg_params.values())
                update_on_kvstore = max_size < 1024 * 1024 * 16
            elif mesh is not None and kv.folds_into_fused_step(mesh):
                # dist spec under a PROCESS-SPANNING mesh (ISSUE 20): the
                # fused step's GSPMD psum over the host-crossing dp axis IS
                # the cross-process aggregation, so the local updater owns
                # the optimizer and the store stays an (idle) identity
                # layer — same contract as the explicit-instance fold above
                update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names, update_on_kvstore):
    """Reference ``model.py:116`` — push initial weights."""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names):
    """Reference ``model.py:145`` — push grads, pull updated weights."""
    from . import telemetry
    from .telemetry import tracing

    with tracing.span("optimizer_update", path="kvstore",
                      params=len(param_arrays)):
        for index, pair in enumerate(zip(param_arrays, grad_arrays)):
            arg_list, grad_list = pair
            if grad_list is None or (isinstance(grad_list, list) and grad_list[0] is None):
                continue
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, arg_list, priority=-index)
            # the per-parameter dispatch storm the fused Module step removes
            # (ISSUE 3) — counted so bench/telemetry expose dispatches_per_step
            telemetry.note_dispatch(1, path="legacy")


def _update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None, param_names=None):
    """Reference ``model.py:157+`` — kvstore aggregation + local updater."""
    from . import telemetry
    from .telemetry import tracing

    with tracing.span("optimizer_update", path="local",
                      params=len(param_arrays)):
        for i, pair in enumerate(zip(param_arrays, grad_arrays)):
            arg_list, grad_list = pair
            if grad_list is None or (isinstance(grad_list, list) and grad_list[0] is None):
                continue
            index = i
            if kvstore:
                name = param_names[index]
                kvstore.push(name, grad_list, priority=-index)
                kvstore.pull(name, grad_list, priority=-index)
            if not isinstance(arg_list, (list, tuple)):
                arg_list, grad_list = [arg_list], [grad_list]
            for k, (w, g) in enumerate(zip(arg_list, grad_list)):
                # one updater state per device copy (reference uses index*num_device+k)
                updater(index * num_device + k, g, w)
                telemetry.note_dispatch(1, path="legacy")


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """``prefix-symbol.json`` + ``prefix-%04d.params`` (reference model.py:384)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)
    logging.info("Saved checkpoint to \"%s\"", param_name)


def load_checkpoint(prefix, epoch):
    """→ (symbol, arg_params, aux_params) (reference model.py:414)."""
    import os

    symbol = None
    if os.path.exists("%s-symbol.json" % prefix):
        symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy training API (reference model.py FeedForward) — a thin veneer
    over Module kept for API completeness; new code should use mx.mod.Module
    or gluon."""

    def __init__(self, symbol, ctx=None, num_epoch=None, optimizer="sgd",
                 initializer=None, arg_params=None, aux_params=None, **kwargs):
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.kwargs = kwargs
        self._module = None

    def fit(self, X, y=None, eval_data=None, eval_metric="acc", epoch_end_callback=None,
            batch_end_callback=None, kvstore="local", logger=None, work_load_list=None):
        from .module import Module
        from .io import NDArrayIter

        if not hasattr(X, "provide_data"):
            X = NDArrayIter(X, y, batch_size=min(128, len(X)))
        label_names = [d[0] for d in X.provide_label] if X.provide_label else None
        mod = Module(self.symbol, data_names=[d[0] for d in X.provide_data],
                     label_names=label_names, context=self.ctx, logger=logger or logging)
        mod.fit(
            X,
            eval_data=eval_data,
            eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback,
            kvstore=kvstore,
            optimizer=self.optimizer,
            optimizer_params=self.kwargs or {"learning_rate": 0.01},
            initializer=self.initializer,
            arg_params=self.arg_params,
            aux_params=self.aux_params,
            num_epoch=self.num_epoch,
        )
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None):
        assert self._module is not None, "call fit first"
        return self._module.predict(X, num_batch=num_batch).asnumpy()

    def trainer_stats(self):
        """The process's last trainhealth row (ISSUE 12; the plane is
        process-global — see ``Module.trainer_stats``); None before fit,
        or with MXNET_TRAINHEALTH off."""
        return self._module.trainer_stats() if self._module is not None \
            else None

    def save(self, prefix, epoch=None):
        save_checkpoint(prefix, epoch if epoch is not None else (self.num_epoch or 0),
                        self.symbol, self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params, aux_params=aux_params,
                           num_epoch=epoch, **kwargs)
