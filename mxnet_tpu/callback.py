"""Training callbacks — reference ``python/mxnet/callback.py``
(do_checkpoint :55, Speedometer :120, log_train_metric, ProgressBar)."""
from __future__ import annotations

import logging
import math
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric", "Speedometer", "ProgressBar"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Checkpoint callback for Module (reference callback.py module_checkpoint)."""
    every = max(int(period), 1)

    def _callback(iter_no, sym=None, arg=None, aux=None):
        epoch = iter_no + 1
        if epoch % every == 0:
            mod.save_checkpoint(prefix, epoch, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end checkpoint callback (reference callback.py:55)."""
    from .model import save_checkpoint

    every = max(int(period), 1)

    def _callback(iter_no, sym, arg, aux):
        epoch = iter_no + 1
        if epoch % every == 0:
            save_checkpoint(prefix, epoch, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period or param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Iter[%d] Batch[%d] Train-%s=%f", param.epoch, param.nbatch, name, value)
        if auto_reset:
            param.eval_metric.reset()

    return _callback


class Speedometer:
    """Log samples/sec every `frequent` batches (log-format parity with
    reference callback.py:120; timing is tracked as a window mark that is
    re-established whenever the batch counter rewinds, i.e. a new epoch).

    With ``MXNET_TELEMETRY`` enabled the rate is also published to the
    telemetry registry (``speedometer_samples_per_sec`` — one source of
    truth for throughput) and the log line grows a trailing
    ``data-wait=N.N%`` field computed from the fit loop's
    ``data_wait_seconds_total`` counter over the same window.  The reference
    log format is untouched when telemetry is off."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._mark = None       # timestamp opening the current window
        self._prev_count = -1
        self._prev_wait = 0.0   # data_wait_seconds_total at window open

    def __call__(self, param):
        count = param.nbatch
        rewound = count < self._prev_count
        self._prev_count = count
        if self._mark is None or rewound:
            self._mark = time.time()
            self._prev_wait = self._wait_total()
            return
        if count % self.frequent:
            return
        window = time.time() - self._mark
        rate = self.frequent * self.batch_size / window
        self._emit(param, count, rate, window)
        self._mark = time.time()
        self._prev_wait = self._wait_total()

    @staticmethod
    def _wait_total():
        from . import telemetry

        if not telemetry.enabled():
            return 0.0
        return telemetry.registry().total("data_wait_seconds_total")

    def _telemetry_suffix(self, rate, window):
        """→ ["data-wait=N.N%"] when telemetry is on, else []."""
        from . import telemetry

        if not telemetry.enabled():
            return []
        telemetry.registry().gauge(
            "speedometer_samples_per_sec", "Speedometer window throughput",
        ).set(rate)
        # the counter is process-global across loops, so a second concurrent
        # fit loop can inflate the delta past the window — clamp to 100%
        wait = self._wait_total() - self._prev_wait
        frac = min(max(wait / window, 0.0), 1.0) if window > 0 else 0.0
        return ["data-wait=%.1f%%" % (100.0 * frac)]

    def _emit(self, param, count, rate, window):
        extra = self._telemetry_suffix(rate, window)
        metric = param.eval_metric
        if metric is None:
            logging.info("\t".join(
                ["Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                 % (param.epoch, count, rate)] + extra))
            return
        pairs = metric.get_name_value()
        if self.auto_reset:
            metric.reset()
        parts = ["Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec" % (param.epoch, count, rate)]
        parts.extend("%s=%f" % (name, value) for name, value in pairs)
        logging.info("\t".join(parts + extra))


class ProgressBar:
    """ASCII progress bar (reference callback.py ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = int(length)
        self.total = float(total)

    def __call__(self, param):
        frac = param.nbatch / self.total
        filled = int(round(self.bar_len * frac))
        bar = "=" * filled + "-" * (self.bar_len - filled)
        logging.info("[%s] %s%s\r", bar, math.ceil(100.0 * frac), "%")
