"""Training callbacks — reference ``python/mxnet/callback.py``
(do_checkpoint :55, Speedometer :120, log_train_metric, ProgressBar)."""
from __future__ import annotations

import logging
import math
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric", "Speedometer", "ProgressBar"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Checkpoint callback for Module (reference callback.py module_checkpoint)."""
    every = max(int(period), 1)

    def _callback(iter_no, sym=None, arg=None, aux=None):
        epoch = iter_no + 1
        if epoch % every == 0:
            mod.save_checkpoint(prefix, epoch, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end checkpoint callback (reference callback.py:55)."""
    from .model import save_checkpoint

    every = max(int(period), 1)

    def _callback(iter_no, sym, arg, aux):
        epoch = iter_no + 1
        if epoch % every == 0:
            save_checkpoint(prefix, epoch, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period or param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Iter[%d] Batch[%d] Train-%s=%f", param.epoch, param.nbatch, name, value)
        if auto_reset:
            param.eval_metric.reset()

    return _callback


class Speedometer:
    """Log samples/sec every `frequent` batches (log-format parity with
    reference callback.py:120; timing is tracked as a window mark that is
    re-established whenever the batch counter rewinds, i.e. a new epoch)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._mark = None       # timestamp opening the current window
        self._prev_count = -1

    def __call__(self, param):
        count = param.nbatch
        rewound = count < self._prev_count
        self._prev_count = count
        if self._mark is None or rewound:
            self._mark = time.time()
            return
        if count % self.frequent:
            return
        rate = self.frequent * self.batch_size / (time.time() - self._mark)
        self._emit(param, count, rate)
        self._mark = time.time()

    def _emit(self, param, count, rate):
        metric = param.eval_metric
        if metric is None:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, count, rate)
            return
        pairs = metric.get_name_value()
        if self.auto_reset:
            metric.reset()
        parts = ["Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec" % (param.epoch, count, rate)]
        parts.extend("%s=%f" % (name, value) for name, value in pairs)
        logging.info("\t".join(parts))


class ProgressBar:
    """ASCII progress bar (reference callback.py ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = int(length)
        self.total = float(total)

    def __call__(self, param):
        frac = param.nbatch / self.total
        filled = int(round(self.bar_len * frac))
        bar = "=" * filled + "-" * (self.bar_len - filled)
        logging.info("[%s] %s%s\r", bar, math.ceil(100.0 * frac), "%")
