"""Optimizers — reference ``python/mxnet/optimizer.py`` (registry at :35) and
the fused update kernels of ``src/operator/optimizer_op.cc``.

Design: every rule is a *pure* function ``(weight, grad, *state, lr, wd, ...)
→ (new_weight, *new_state)`` so the same rule runs eagerly (Updater path) or
fused inside a jitted/pjit'ed train step (the TPU-performance path — the
reference fused these as C++ kernels for the same reason).  Optimizer classes
wrap the rules with MXNet's lr/wd multiplier & scheduling semantics.
"""
from __future__ import annotations

import math
import pickle

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray, _wrap, array

__all__ = [
    "Optimizer",
    "SGD",
    "NAG",
    "Signum",
    "SGLD",
    "Adam",
    "AdaGrad",
    "AdaDelta",
    "Adamax",
    "Nadam",
    "RMSProp",
    "Ftrl",
    "Ftml",
    "DCASGD",
    "LBSGD",
    "Updater",
    "get_updater",
    "create",
    "register",
]

_OPT_REGISTRY = {}


def register(klass):
    """Register an Optimizer subclass under its lowercase name (reference
    Optimizer.register)."""
    _OPT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if name.lower() not in _OPT_REGISTRY:
        raise MXNetError("Optimizer %s not registered (have %s)" % (name, sorted(_OPT_REGISTRY)))
    return _OPT_REGISTRY[name.lower()](**kwargs)


class Optimizer:
    """Base optimizer (reference optimizer.py:35).

    Tracks per-parameter lr/wd multipliers, update counts, and optional
    multi-precision (bf16 weights with f32 master copy — the TPU analog of
    the reference's fp16/fp32 multi-precision path).
    """

    def __init__(
        self,
        rescale_grad=1.0,
        param_idx2name=None,
        wd=0.0,
        clip_gradient=None,
        learning_rate=0.01,
        lr_scheduler=None,
        sym=None,
        begin_num_update=0,
        multi_precision=False,
        param_dict=None,
    ):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.sym_info = None
        if sym is not None:
            self.sym_info = (sym.attr_dict(), sym.list_arguments())

    # -- multipliers (reference optimizer.py set_lr_mult/set_wd_mult) ------
    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # bias/gamma/beta traditionally exempt from wd (reference :309)
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler is not None else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- to be provided by subclasses ---------------------------------------
    def create_state(self, index, weight):
        raise NotImplementedError

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def fused_step_kind(self):
        """Kind tag consumed by the Module fused train step
        (``module/fused_step.py`` + ``ops.optimizer_ops.fused_update``), or
        None when this optimizer's update cannot be folded into the jitted
        step graph (stateful host logic, sparse-only rules, multi-precision
        master-weight tuples) — the Module then routes through the legacy
        per-parameter Updater path."""
        return None

    def create_state_multi_precision(self, index, weight):
        """f32 master weights for low-precision params (reference :201-249)."""
        import jax.numpy as jnp

        if self.multi_precision and weight.dtype in (np.float16, jnp.bfloat16):
            master = weight.astype("float32")
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    def update_multi_precision(self, index, weight, grad, state):
        import jax.numpy as jnp

        from .ndarray.sparse import BaseSparseNDArray, RowSparseNDArray

        if isinstance(grad, BaseSparseNDArray) and not (
            isinstance(grad, RowSparseNDArray) and getattr(self, "_handles_sparse", False)
        ):
            # only row_sparse has dedicated rules; everything else densifies
            grad = grad.todense()
        if self.multi_precision and isinstance(state, tuple) and len(state) == 2 and isinstance(state[0], NDArray):
            master, base_state = state
            if isinstance(grad, BaseSparseNDArray):
                grad = grad.todense()
            self.update(index, master, grad.astype("float32"), base_state)
            weight._rebind(master._data.astype(weight._data.dtype))
        else:
            self.update(index, weight, grad, state)

    # -- shared grad preprocessing ------------------------------------------
    def _preprocess(self, grad):
        import jax.numpy as jnp

        g = grad._data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def serialize(self):
        return pickle.dumps(self)

    @staticmethod
    def deserialize(buf):
        return pickle.loads(buf)


# ---------------------------------------------------------------------------
# pure update rules (usable inside jit; see parallel.trainer for fused use)
# ---------------------------------------------------------------------------


def sgd_rule(w, g, mom, *, lr, wd, momentum=0.0):
    """w -= lr*(g + wd*w) with momentum (reference sgd_mom_update)."""
    g = g + wd * w
    if mom is None:
        return w - lr * g, None
    new_mom = momentum * mom - lr * g
    return w + new_mom, new_mom


def nag_rule(w, g, mom, *, lr, wd, momentum=0.0):
    """Nesterov momentum (reference NAG optimizer)."""
    g = g + wd * w
    new_mom = momentum * mom + g
    return w - lr * (g + momentum * new_mom), new_mom


def adam_rule(w, g, m, v, t, *, lr, wd, beta1=0.9, beta2=0.999, epsilon=1e-8):
    import jax.numpy as jnp

    g = g + wd * w
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    coef1 = 1.0 - beta1**t
    coef2 = 1.0 - beta2**t
    lr_t = lr * (coef2**0.5) / coef1
    return w - lr_t * m / (jnp.sqrt(v) + epsilon), m, v


def rmsprop_rule(w, g, n, *, lr, wd, gamma1=0.9, epsilon=1e-8):
    import jax.numpy as jnp

    g = g + wd * w
    n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    return w - lr * g / jnp.sqrt(n + epsilon), n


def rmspropalex_rule(w, g, n, gavg, delta, *, lr, wd, gamma1=0.9, gamma2=0.9, epsilon=1e-8):
    import jax.numpy as jnp

    g = g + wd * w
    n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    gavg = (1 - gamma1) * g + gamma1 * gavg
    delta = gamma2 * delta - lr * g / jnp.sqrt(n - jnp.square(gavg) + epsilon)
    return w + delta, n, gavg, delta


def adagrad_rule(w, g, hist, *, lr, wd, epsilon=1e-7):
    import jax.numpy as jnp

    g = g + wd * w
    hist = hist + jnp.square(g)
    return w - lr * g / (jnp.sqrt(hist) + epsilon), hist


def adadelta_rule(w, g, acc_g, acc_delta, *, lr, wd, rho=0.90, epsilon=1e-5):
    import jax.numpy as jnp

    g = g + wd * w
    acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(acc_g + epsilon) * g
    acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return w - delta, acc_g, acc_delta


def adamax_rule(w, g, m, u, t, *, lr, wd, beta1=0.9, beta2=0.999):
    import jax.numpy as jnp

    g = g + wd * w
    m = beta1 * m + (1 - beta1) * g
    u = jnp.maximum(beta2 * u, jnp.abs(g))
    lr_t = lr / (1.0 - beta1**t)
    return w - lr_t * m / (u + 1e-8), m, u


def nadam_rule(w, g, m, v, t, *, lr, wd, beta1=0.9, beta2=0.999, epsilon=1e-8, schedule_decay=0.004):
    import jax.numpy as jnp

    g = g + wd * w
    mom_t = beta1 * (1.0 - 0.5 * 0.96 ** (t * schedule_decay))
    mom_t1 = beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * schedule_decay))
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    g_prime = g / (1.0 - mom_t)
    m_prime = m / (1.0 - beta1 ** (t + 1))
    v_prime = v / (1.0 - beta2**t)
    m_bar = (1.0 - mom_t) * g_prime + mom_t1 * m_prime
    return w - lr * m_bar / (jnp.sqrt(v_prime) + epsilon), m, v


def ftrl_rule(w, g, z, n, *, lr, wd, lamda1=0.01, beta=1.0):
    import jax.numpy as jnp

    g = g  # wd enters via the prox term
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    z = z + g - sigma * w
    new_w = jnp.where(
        jnp.abs(z) > lamda1,
        -(z - jnp.sign(z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
        jnp.zeros_like(w),
    )
    return new_w, z, new_n


def signum_rule(w, g, mom, *, lr, wd, momentum=0.0, wd_lh=0.0):
    import jax.numpy as jnp

    if mom is None:
        return (1 - lr * wd_lh) * w - lr * jnp.sign(g + wd * w), None
    new_mom = momentum * mom - (1 - momentum) * (g + wd * w)
    return (1 - lr * wd_lh) * w + lr * jnp.sign(new_mom), new_mom


def ftml_rule(w, g, d, v, z, t, *, lr, wd, beta1=0.6, beta2=0.999, epsilon=1e-8):
    import jax.numpy as jnp

    g = g + wd * w
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1**t) / lr * (jnp.sqrt(v / (1 - beta2**t)) + epsilon)
    sigma = d_t - beta1 * d
    z = beta1 * z + (1 - beta1) * g - sigma * w
    new_w = -z / d_t
    return new_w, d_t, v, z


RULES = {
    "sgd": sgd_rule,
    "nag": nag_rule,
    "adam": adam_rule,
    "rmsprop": rmsprop_rule,
    "adagrad": adagrad_rule,
    "adadelta": adadelta_rule,
    "adamax": adamax_rule,
    "nadam": nadam_rule,
    "ftrl": ftrl_rule,
    "signum": signum_rule,
    "ftml": ftml_rule,
}


# ---------------------------------------------------------------------------
# optimizer classes
# ---------------------------------------------------------------------------


def _zeros_like_nd(w):
    import jax.numpy as jnp

    return _wrap(jnp.zeros_like(w._data))


@register
class SGD(Optimizer):
    """SGD with momentum & multi-precision (reference optimizer.py SGD).

    Row-sparse gradients take the lazy path: only rows present in the
    gradient are updated (reference sgd_update/sgd_mom_update sparse kernels,
    src/operator/optimizer_op.cc) — on TPU this is a gather/scatter over the
    touched rows, the embedding-training fast path.
    """

    _handles_sparse = True

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def fused_step_kind(self):
        # subclasses (LBSGD) override update() with host-side logic the
        # fused graph can't reproduce — only plain SGD folds in.  One kind
        # for both momentum modes: like sgd_rule, the fused kernel picks
        # plain-vs-momentum per parameter from the presence of a state slot
        if type(self) is not SGD or self.multi_precision:
            return None
        return "sgd"

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _zeros_like_nd(weight)

    def _sparse_update(self, index, weight, grad, state):
        import jax.numpy as jnp

        lr, wd = self._get_lr(index), self._get_wd(index)
        rows = grad._aux["indices"]
        g = grad._aux["data"] * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        w = weight._data
        w_rows = w[rows]
        g = g + wd * w_rows
        if state is None:
            weight._rebind(w.at[rows].add(-lr * g))
        else:
            mom_rows = self.momentum * state._data[rows] - lr * g
            state._rebind(state._data.at[rows].set(mom_rows))
            weight._rebind(w.at[rows].add(mom_rows))

    def update(self, index, weight, grad, state):
        from .ndarray.sparse import RowSparseNDArray

        self._update_count(index)
        if isinstance(grad, RowSparseNDArray):
            if self.lazy_update:
                return self._sparse_update(index, weight, grad, state)
            grad = grad.todense()
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad)
        mom = state._data if state is not None else None
        new_w, new_mom = sgd_rule(weight._data, g, mom, lr=lr, wd=wd, momentum=self.momentum)
        weight._rebind(new_w)
        if state is not None:
            state._rebind(new_mom)


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return _zeros_like_nd(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        g = self._preprocess(grad)
        new_w, new_mom = nag_rule(
            weight._data, g, state._data, lr=self._get_lr(index), wd=self._get_wd(index), momentum=self.momentum
        )
        weight._rebind(new_w)
        state._rebind(new_mom)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        return _zeros_like_nd(weight) if self.momentum != 0.0 else None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        g = self._preprocess(grad)
        new_w, new_mom = signum_rule(
            weight._data,
            g,
            state._data if state is not None else None,
            lr=self._get_lr(index),
            wd=self._get_wd(index),
            momentum=self.momentum,
            wd_lh=self.wd_lh,
        )
        weight._rebind(new_w)
        if state is not None:
            state._rebind(new_mom)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer.py SGLD)."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        import jax

        from . import random as _rnd

        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad) + wd * weight._data
        noise = jax.random.normal(_rnd.next_key(), weight.shape, dtype=weight._data.dtype) * math.sqrt(lr)
        weight._rebind(weight._data - lr / 2 * g + noise)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = _zeros_like_nd(weight) if self.momentum != 0.0 else None
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad)
        mom, prev = state
        comp = g + wd * weight._data + self.lamda * g * g * (weight._data - prev._data)
        if mom is not None:
            new_mom = self.momentum * mom._data - lr * comp
            mom._rebind(new_mom)
            upd = new_mom
        else:
            upd = -lr * comp
        prev._rebind(weight._data)
        weight._rebind(weight._data + upd)


@register
class Adam(Optimizer):
    _handles_sparse = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def fused_step_kind(self):
        if type(self) is not Adam or self.multi_precision:
            return None
        return "adam"

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def _sparse_update(self, index, weight, grad, state, t):
        """Lazy row-sparse adam (reference adam_update row_sparse kernel)."""
        import jax.numpy as jnp

        lr, wd = self._get_lr(index), self._get_wd(index)
        rows = grad._aux["indices"]
        g = grad._aux["data"] * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        m, v = state
        w_rows = weight._data[rows]
        g = g + wd * w_rows
        m_rows = self.beta1 * m._data[rows] + (1 - self.beta1) * g
        v_rows = self.beta2 * v._data[rows] + (1 - self.beta2) * g * g
        lr_t = lr * np.sqrt(1 - self.beta2**t) / (1 - self.beta1**t)
        upd = -lr_t * m_rows / (jnp.sqrt(v_rows) + self.epsilon)
        m._rebind(m._data.at[rows].set(m_rows))
        v._rebind(v._data.at[rows].set(v_rows))
        weight._rebind(weight._data.at[rows].add(upd))

    def update(self, index, weight, grad, state):
        from .ndarray.sparse import RowSparseNDArray

        self._update_count(index)
        t = self._index_update_count[index]
        if isinstance(grad, RowSparseNDArray):
            if self.lazy_update:
                return self._sparse_update(index, weight, grad, state, t)
            grad = grad.todense()
        g = self._preprocess(grad)
        m, v = state
        new_w, new_m, new_v = adam_rule(
            weight._data, g, m._data, v._data, t,
            lr=self._get_lr(index), wd=self._get_wd(index),
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
        )
        weight._rebind(new_w)
        m._rebind(new_m)
        v._rebind(new_v)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _zeros_like_nd(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        g = self._preprocess(grad)
        new_w, new_h = adagrad_rule(
            weight._data, g, state._data, lr=self._get_lr(index), wd=self._get_wd(index), epsilon=self.float_stable_eps
        )
        weight._rebind(new_w)
        state._rebind(new_h)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        g = self._preprocess(grad)
        acc_g, acc_d = state
        new_w, ng, ndl = adadelta_rule(
            weight._data, g, acc_g._data, acc_d._data,
            lr=self._get_lr(index), wd=self._get_wd(index), rho=self.rho, epsilon=self.epsilon,
        )
        weight._rebind(new_w)
        acc_g._rebind(ng)
        acc_d._rebind(ndl)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        g = self._preprocess(grad)
        m, u = state
        new_w, nm, nu = adamax_rule(
            weight._data, g, m._data, u._data, t,
            lr=self._get_lr(index), wd=self._get_wd(index), beta1=self.beta1, beta2=self.beta2,
        )
        weight._rebind(new_w)
        m._rebind(nm)
        u._rebind(nu)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        g = self._preprocess(grad)
        m, v = state
        new_w, nm, nv = nadam_rule(
            weight._data, g, m._data, v._data, t,
            lr=self._get_lr(index), wd=self._get_wd(index),
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon, schedule_decay=self.schedule_decay,
        )
        weight._rebind(new_w)
        m._rebind(nm)
        v._rebind(nv)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2, self.epsilon = gamma1, gamma2, epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_zeros_like_nd(weight), _zeros_like_nd(weight), _zeros_like_nd(weight))
        return _zeros_like_nd(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        g = self._preprocess(grad)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if self.centered:
            n, gavg, delta = state
            new_w, nn, ng, nd_ = rmspropalex_rule(
                weight._data, g, n._data, gavg._data, delta._data,
                lr=lr, wd=wd, gamma1=self.gamma1, gamma2=self.gamma2, epsilon=self.epsilon,
            )
            n._rebind(nn)
            gavg._rebind(ng)
            delta._rebind(nd_)
        else:
            new_w, nn = rmsprop_rule(weight._data, g, state._data, lr=lr, wd=wd, gamma1=self.gamma1, epsilon=self.epsilon)
            state._rebind(nn)
        if self.clip_weights:
            import jax.numpy as jnp

            new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
        weight._rebind(new_w)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        g = self._preprocess(grad)
        z, n = state
        new_w, nz, nn = ftrl_rule(
            weight._data, g, z._data, n._data,
            lr=self._get_lr(index), wd=self._get_wd(index), lamda1=self.lamda1, beta=self.beta,
        )
        weight._rebind(new_w)
        z._rebind(nz)
        n._rebind(nn)


@register
class Ftml(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight), _zeros_like_nd(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        g = self._preprocess(grad)
        d, v, z = state
        new_w, ndt, nv, nz = ftml_rule(
            weight._data, g, d._data, v._data, z._data, t,
            lr=self._get_lr(index), wd=self._get_wd(index),
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
        )
        weight._rebind(new_w)
        d._rebind(ndt)
        v._rebind(nv)
        z._rebind(nz)


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise adaptive rate
    (reference optimizer.py LBSGD, simplified to the LARS core)."""

    def __init__(self, momentum=0.0, eta=0.001, **kwargs):
        super().__init__(momentum=momentum, **kwargs)
        self.eta = eta

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad)
        wnorm = jnp.linalg.norm(weight._data)
        gnorm = jnp.linalg.norm(g)
        lars = jnp.where(
            (wnorm > 0) & (gnorm > 0), self.eta * wnorm / (gnorm + wd * wnorm + 1e-9), 1.0
        )
        mom = state._data if state is not None else None
        new_w, new_mom = sgd_rule(weight._data, g, mom, lr=lr * lars, wd=wd, momentum=self.momentum)
        weight._rebind(new_w)
        if state is not None:
            state._rebind(new_mom)


# 'Test' optimizer used by reference unit tests
@register
class Test(Optimizer):
    def create_state(self, index, weight):
        return _zeros_like_nd(weight)

    def update(self, index, weight, grad, state):
        weight._rebind(weight._data - self.lr * self._preprocess(grad))


class Updater:
    """Applies an optimizer locally, managing per-key states (reference
    optimizer.py Updater; the kvstore 'local update' path)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad, self.states[index])

    def get_states(self, dump_optimizer=False):
        states = {
            k: (v.asnumpy() if isinstance(v, NDArray) else _state_np(v)) for k, v in self.states.items()
        }
        payload = (states, self.optimizer) if dump_optimizer else states
        return pickle.dumps(payload)

    def set_states(self, states_bytes):
        data = pickle.loads(states_bytes)
        if isinstance(data, tuple):
            states, self.optimizer = data
        else:
            states = data
        for k, v in states.items():
            self.states[k] = _state_nd(v)
            self.states_synced[k] = True


def _state_np(state):
    if state is None:
        return None
    if isinstance(state, NDArray):
        return state.asnumpy()
    return tuple(_state_np(s) for s in state)


def _state_nd(state):
    if state is None:
        return None
    if isinstance(state, np.ndarray):
        return array(state)
    return tuple(_state_nd(s) for s in state)


def get_updater(optimizer):
    return Updater(optimizer)
