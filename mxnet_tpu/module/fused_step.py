"""Fused train-step executor for the symbolic Module stack (ISSUE 3).

The legacy Module step runs the forward graph TWICE (``Executor.forward``
dispatches it, ``Executor.backward`` re-traces it inside ``jax.vjp``) and
then issues a per-parameter storm of tiny eager optimizer dispatches
(``model._update_params``), with zero buffer donation.  This module collapses
the whole training step into ONE donated jit dispatch — the whole-graph
fusion win TVM/Relay demonstrate, and the idiom the gluon path already
proves in ``gluon.functional.make_train_step``:

    (params, grads_in, opt_state, aux, data, key, lr, wd)
        -> (new_params, new_opt_state, new_aux, outputs, grads)

- loss heads AND gradients come from a single ``jax.vjp`` pass over the
  executor's graph function (no duplicated forward);
- the optimizer update is folded into the same graph through the pure
  kernels in ``ops.optimizer_ops`` (``fused_update``), with per-parameter
  lr/wd (schedulers, ``lr_mult``/``wd_mult``) arriving as TRACED vectors so
  decays cost zero recompiles;
- BatchNorm aux statistics fold back functionally, exactly like the legacy
  forward;
- param / grad / optimizer-state / aux buffers are donated, so steady-state
  HBM traffic matches an in-place engine;
- jax.jit caches per shape signature: ``Module.reshape`` costs exactly one
  retrace, switching back costs none.

``Module.forward_backward`` stages the batch, ``Module.update`` dispatches;
eligibility and the ``MXNET_MODULE_FUSED_STEP`` escape hatch live here (see
``fused_ineligible_reason`` and docs/PERF_NOTES.md "Fused Module train
step").  Fallbacks route through the untouched legacy path and are counted
in the telemetry registry (``module_fused_fallback_total{reason}``).
"""
from __future__ import annotations

import numpy as np

from .. import telemetry
from ..base import MXNetError, env_flag
from ..ndarray.ndarray import NDArray, _wrap

__all__ = ["FusedStepper", "fused_enabled", "fused_ineligible_reason"]


def fused_enabled():
    """``MXNET_MODULE_FUSED_STEP`` gate (docs/ENV_VARS.md) — default ON."""
    return env_flag("MXNET_MODULE_FUSED_STEP", default="1")


def fused_ineligible_reason(module):
    """None when the fused path can take this Module's next train step, else
    a short tag naming why not (doubles as the fallback-counter label).

    The conditions mirror what the fused graph cannot express: a monitor
    needs un-jitted per-node callbacks, ``grad_req`` mixes ("add"/"null")
    need the executor's accumulate-into-buffer semantics, kvstore updates
    leave the device, a mesh feed shards through the legacy forward, and
    optimizers without a ``fused_step_kind`` carry host-side state.
    Explicit ``backward(out_grads=...)`` calls never reach here — only
    ``forward_backward`` stages fused steps, so user-supplied head
    cotangents always take the legacy path by construction.
    """
    if not fused_enabled():
        return "disabled"
    if not module.optimizer_initialized:
        return "no_optimizer"
    if module._exec is None or module._exec._monitor is not None:
        return "monitor"
    if module._mesh is not None:
        return "mesh"
    if module._kvstore is not None or module._update_on_kvstore:
        return "kvstore"
    if module._updater is None:
        return "no_optimizer"
    if module.inputs_need_grad:
        return "inputs_need_grad"
    req = module._exec._grad_req
    for n in module._param_names:
        if req.get(n, "null") != "write":
            return "grad_req"
        if module._exec.grad_dict.get(n) is None:
            return "grad_req"
    opt = module._optimizer
    if opt is None or opt.fused_step_kind() is None:
        return "optimizer"
    return None


def _hp_signature(opt):
    """The optimizer hyperparams the fused graph folds in as constants
    (lr/wd stay live — they enter as traced vectors every step).  The
    Module rebuilds the stepper when this changes, so mutating e.g.
    ``rescale_grad`` or ``momentum`` mid-run behaves like the legacy path
    instead of silently using stale values."""
    kind = opt.fused_step_kind()
    sig = (kind, float(opt.rescale_grad),
           None if opt.clip_gradient is None else float(opt.clip_gradient))
    if kind == "sgd":
        sig += (float(opt.momentum),)
    elif kind == "adam":
        sig += (float(opt.beta1), float(opt.beta2), float(opt.epsilon))
    return sig


def _state_leaves(state):
    """Flatten one Updater state slot (None | NDArray | tuple) to a list of
    jax arrays for the jitted step."""
    if state is None:
        return []
    if isinstance(state, NDArray):
        return [state._data]
    return [s._data for s in state]


def _commit_state(state, new_leaves):
    """Write the fused step's returned state leaves back into the Updater's
    NDArrays (keeps save/load_optimizer_states working unchanged)."""
    if state is None:
        assert not new_leaves
        return
    if isinstance(state, NDArray):
        state._rebind(new_leaves[0])
        return
    for s, v in zip(state, new_leaves):
        s._rebind(v)


def _build_step_fn(graph_fn, arg_names, diff_names, const_names, kind, hp,
                   nancheck=False):
    """The pure fused step: one vjp over the executor graph + the in-graph
    optimizer fold.  Closed over only static structure (names, kind, static
    hyperparams, the nancheck flag) so one jitted instance survives re-binds
    of the same symbol and re-traces only on new shape signatures.

    With ``nancheck`` the step also returns a scalar ``finite`` flag —
    ``all(isfinite(heads)) & all(isfinite(grads))`` reduced INSIDE the same
    donated jit, so the check adds no dispatch and no sync (the caller reads
    the flag one step later, when it has materialized for free)."""
    import jax
    import jax.numpy as jnp

    from ..ops.optimizer_ops import fused_update

    def step(diff_vals, grads_in, opt_state, aux_vals, const_vals, key,
             lr_vec, wd_vec):
        # grads_in is donated purely so XLA can recycle the standing grad
        # buffers for the returned gradients
        del grads_in

        def f(dvals):
            env = dict(zip(const_names, const_vals))
            env.update(zip(diff_names, dvals))
            return graph_fn([env[n] for n in arg_names], aux_vals, key)

        heads, vjp_fn, new_aux = jax.vjp(f, diff_vals, has_aux=True)
        (grads,) = vjp_fn([jnp.ones_like(h) for h in heads])
        new_params, new_state = [], []
        for i, (w, g) in enumerate(zip(diff_vals, grads)):
            st = tuple(opt_state[i])
            # like sgd_rule: a parameter updates with momentum iff it HAS a
            # momentum slot (created when the optimizer's momentum was set),
            # so mid-run momentum edits behave exactly like the legacy path
            k = ("sgd_mom" if st else "sgd") if kind == "sgd" else kind
            new_w, new_st = fused_update(k, w, g, st,
                                         lr=lr_vec[i], wd=wd_vec[i], **hp)
            new_params.append(new_w)
            new_state.append(list(new_st))
        if not nancheck:
            return new_params, new_state, new_aux, heads, grads
        finite = jnp.bool_(True)
        for h in heads:
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(h)))
        for g in grads:
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
        return new_params, new_state, new_aux, heads, grads, finite

    return step


class FusedStepper:
    """Per-Module fused-step cache: builds the jitted step once (per
    optimizer configuration) and re-dispatches it for every eligible step;
    jax.jit's executable cache provides the per-shape-signature caching."""

    def __init__(self, module):
        import jax

        exec_ = module._exec
        opt = module._optimizer
        self._opt = opt
        self._kind = opt.fused_step_kind()
        assert self._kind is not None
        self._hp_sig = _hp_signature(opt)
        self._arg_names = list(exec_._arg_names)
        self._aux_names = list(exec_._aux_names)
        self._diff_names = list(module._param_names)
        dset = set(self._diff_names)
        self._const_names = [n for n in self._arg_names if n not in dset]
        hp = {"rescale_grad": float(opt.rescale_grad),
              "clip_gradient": (-1.0 if opt.clip_gradient is None
                                else float(opt.clip_gradient))}
        if self._kind == "sgd":
            hp["momentum"] = float(opt.momentum)
        elif self._kind == "adam":
            hp.update(beta1=float(opt.beta1), beta2=float(opt.beta2),
                      epsilon=float(opt.epsilon))
        self._nancheck = env_flag("MXNET_NANCHECK")
        self._nsteps = 0
        self._pending_flag = None  # (finite device scalar, step number)
        fn = _build_step_fn(exec_._graph_fn(True), self._arg_names,
                            self._diff_names, self._const_names,
                            self._kind, hp, nancheck=self._nancheck)
        self._jit = jax.jit(fn, donate_argnums=(0, 1, 2, 3))
        # compile/steady-state accounting (identity when telemetry is off)
        self._step = telemetry.instrument_step(self._jit,
                                               name="module_fused_step")

    def cache_size(self):
        """Number of compiled executables (one per shape signature)."""
        size = getattr(self._jit, "_cache_size", None)
        return size() if size is not None else None

    def stale(self, module):
        """True when the Module's optimizer (or a folded-in hyperparam, or
        the MXNET_NANCHECK gate — it changes the step's output structure)
        changed since this stepper was built — caller rebuilds."""
        return (module._optimizer is not self._opt
                or _hp_signature(module._optimizer) != self._hp_sig
                or env_flag("MXNET_NANCHECK") != self._nancheck)

    def check_nonfinite(self):
        """Raise if the PREVIOUS step's folded isfinite flag tripped.

        The flag is an output of the fused jit, so reading it right after
        dispatch would add the per-step sync the fold exists to avoid;
        instead ``run`` checks it just before dispatching the next step, by
        which point it is long materialized (the next step consumes the
        previous outputs anyway).  The error therefore surfaces one update()
        late but NAMES the offending step."""
        if self._pending_flag is None:
            return
        flag, stepno = self._pending_flag
        self._pending_flag = None
        if not bool(flag):
            telemetry.note_nonfinite("fused")
            raise MXNetError(
                "MXNET_NANCHECK: non-finite loss/gradient in fused train "
                "step %d (detected before step %d: the flag is folded into "
                "the fused dispatch and read one step later to avoid a "
                "per-step sync)" % (stepno, stepno + 1))

    def run(self, module):
        """Dispatch ONE fused step over the feed already staged in the
        executor's arg buffers, then commit params / optimizer state / aux /
        outputs / grads.  Consumes exactly one RNG key (like the legacy
        forward), so seeded runs stay reproducible across paths."""
        from .. import random as _rnd

        exec_ = module._exec
        opt = self._opt
        updater = module._updater
        diff_vals = [exec_.arg_dict[n]._data for n in self._diff_names]
        grads_in = [exec_.grad_dict[n]._data for n in self._diff_names]
        const_vals = [exec_.arg_dict[n]._data for n in self._const_names]
        aux_vals = [exec_.aux_dict[n]._data for n in self._aux_names]
        states, leaves = [], []
        for i, n in enumerate(self._diff_names):
            if i not in updater.states:
                updater.states[i] = opt.create_state(i, exec_.arg_dict[n])
                updater.states_synced[i] = True
            states.append(updater.states[i])
            leaves.append(_state_leaves(updater.states[i]))
        # host-side hyperparam prep, O(P) python and zero dispatches: update
        # counts first (the legacy Updater order), then read lr/wd through
        # the optimizer's scheduler/multiplier logic; adam's bias correction
        # folds into lr so the in-graph kernel stays schedule-free
        for i in range(len(self._diff_names)):
            opt._update_count(i)
        lrs, wds = [], []
        for i in range(len(self._diff_names)):
            lr, wd = opt._get_lr(i), opt._get_wd(i)
            if self._kind == "adam":
                t = opt._index_update_count[i]
                lr *= (1.0 - opt.beta2 ** t) ** 0.5 / (1.0 - opt.beta1 ** t)
            lrs.append(lr)
            wds.append(wd)
        key = _rnd.next_key()
        if self._nancheck:
            self.check_nonfinite()
        out = self._step(
            diff_vals, grads_in, leaves, aux_vals, const_vals, key,
            np.asarray(lrs, np.float32), np.asarray(wds, np.float32))
        if self._nancheck:
            new_params, new_state, new_aux, heads, grads, finite = out
            self._nsteps += 1
            self._pending_flag = (finite, self._nsteps)
        else:
            new_params, new_state, new_aux, heads, grads = out
            self._nsteps += 1
        for n, v in zip(self._diff_names, new_params):
            exec_.arg_dict[n]._rebind(v)
        for n, g in zip(self._diff_names, grads):
            exec_.grad_dict[n]._rebind(g)
        for n, v in zip(self._aux_names, new_aux):
            exec_.aux_dict[n]._rebind(v)
        for st, new_leaves in zip(states, new_state):
            _commit_state(st, new_leaves)
        exec_.outputs = [_wrap(h) for h in heads]
        exec_._last_key = key
        exec_._last_is_train = True
