"""Fused train-step executor for the symbolic Module stack (ISSUE 3).

The legacy Module step runs the forward graph TWICE (``Executor.forward``
dispatches it, ``Executor.backward`` re-traces it inside ``jax.vjp``) and
then issues a per-parameter storm of tiny eager optimizer dispatches
(``model._update_params``), with zero buffer donation.  This module collapses
the whole training step into ONE donated jit dispatch — the whole-graph
fusion win TVM/Relay demonstrate, and the idiom the gluon path already
proves in ``gluon.functional.make_train_step``:

    (params, grads_in, opt_state, aux, data, key, lr, wd)
        -> (new_params, new_opt_state, new_aux, outputs, grads)

- loss heads AND gradients come from a single ``jax.vjp`` pass over the
  executor's graph function (no duplicated forward);
- the optimizer update is folded into the same graph through the pure
  kernels in ``ops.optimizer_ops`` (``fused_update``), with per-parameter
  lr/wd (schedulers, ``lr_mult``/``wd_mult``) arriving as TRACED vectors so
  decays cost zero recompiles;
- BatchNorm aux statistics fold back functionally, exactly like the legacy
  forward;
- param / grad / optimizer-state / aux buffers are donated, so steady-state
  HBM traffic matches an in-place engine;
- jax.jit caches per shape signature: ``Module.reshape`` costs exactly one
  retrace, switching back costs none.

``Module.forward_backward`` stages the batch, ``Module.update`` dispatches;
eligibility and the ``MXNET_MODULE_FUSED_STEP`` escape hatch live here (see
``fused_ineligible_reason`` and docs/PERF_NOTES.md "Fused Module train
step").  Fallbacks route through the untouched legacy path and are counted
in the telemetry registry (``module_fused_fallback_total{reason}``).

**Sharded (mesh) fused step — ISSUE 5.**  A ``mesh=`` Module (dp-sharded
batch feed, ``parallel.mesh``) used to hard-fall-back to the legacy path;
now an eligible mesh-fed Module runs the whole multi-chip step as the same
ONE donated jit, sharding-annotated: the batch enters dp-sharded (staged by
``Module._stage_batch`` / the prefetch path), params/aux/grads are pinned
replicated via ``out_shardings``, and GSPMD derives the dp gradient psum
*inside* the compiled step — the collective overlaps compute on ICI instead
of serializing at the Python boundary.  Opt-in ``MXNET_FUSED_ZERO=1``
switches the optimizer state (and the returned grads) to the ZeRO-1 layout
(``parallel.zero_shard_spec``): GSPMD reduce-scatters grads over dp, each
device updates its 1/dp state shard, and the updated params allgather back
to replicated — all in the same XLA module.
"""
from __future__ import annotations

import numpy as np

from .. import telemetry
from ..base import MXNetError, env_flag
from ..ndarray.ndarray import NDArray, _wrap

__all__ = ["FusedStepper", "fused_enabled", "fused_ineligible_reason",
           "fused_zero_enabled"]

_DP_AXIS = "dp"  # the mesh axis the Module batch feed shards over


def fused_enabled():
    """``MXNET_MODULE_FUSED_STEP`` gate (docs/ENV_VARS.md) — default ON."""
    return env_flag("MXNET_MODULE_FUSED_STEP", default="1")


def fused_zero_enabled():
    """``MXNET_FUSED_ZERO`` gate (docs/ENV_VARS.md) — default OFF.  Only
    consulted on the mesh path: ZeRO-1 sharding of optimizer state over dp."""
    return env_flag("MXNET_FUSED_ZERO")


def fused_donate_enabled():
    """``MXNET_FUSED_DONATE`` gate (docs/ENV_VARS.md) — default ON.

    ``0`` builds the fused step WITHOUT donated operands.  The use case:
    restored *donated* executables are skipped on the CPU backend
    (the donation hazard, ``compile_cache.py`` docstring), so a CPU pod
    restart re-pays the train-step compile even with ``MXNET_AOT_CACHE``
    set.  Turning donation off makes the disk restore legal again — the
    warm-restart CI (``ci/check_pod_train.py``) runs its second launch this
    way to prove every rank restores the identical executable.  Costs the
    donation's buffer recycling (params/grads/state copies per step), so
    keep the default on TPU."""
    return env_flag("MXNET_FUSED_DONATE", default="1")


def fused_ineligible_reason(module):
    """None when the fused path can take this Module's next train step, else
    a short tag naming why not (doubles as the fallback-counter label).

    The conditions mirror what the fused graph cannot express: a monitor
    needs un-jitted per-node callbacks, ``grad_req`` mixes ("add"/"null")
    need the executor's accumulate-into-buffer semantics, dist kvstores
    aggregate across processes outside the step, and optimizers without a
    ``fused_step_kind`` carry host-side state.  A mesh feed is fused when
    the mesh carries the ``dp`` batch axis (the in-step psum replaces the
    legacy sharded forward); a local-family kvstore under such a mesh folds
    into that psum (``KVStore.folds_into_fused_step``) instead of forcing
    the eager push/pull loop.  Mesh-*unsupported-feature* steps surface the
    feature's own reason (``monitor``/``grad_req``/``optimizer``/...), not
    the old blanket ``"mesh"``; a mesh without a dp axis is ``mesh_no_dp``.
    Explicit ``backward(out_grads=...)`` calls never reach here — only
    ``forward_backward`` stages fused steps, so user-supplied head
    cotangents always take the legacy path by construction.
    """
    if not fused_enabled():
        return "disabled"
    if not module.optimizer_initialized:
        return "no_optimizer"
    if module._exec is None or module._exec._monitor is not None:
        return "monitor"
    if module._kvstore is not None or module._update_on_kvstore:
        kv = module._kvstore
        folds = (module._mesh is not None and kv is not None
                 and not module._update_on_kvstore
                 and kv.folds_into_fused_step(module._mesh))
        if not folds:
            if kv is not None and kv._is_dist:
                # dist store over a single-host mesh: the cross-process DCN
                # aggregation happens outside the local step.  (Under a
                # PROCESS-SPANNING mesh dist stores fold — GSPMD's in-step
                # psum over the host-crossing dp axis is that aggregation.)
                return "kvstore_dist"
            return "kvstore"
        # store folded under the dp mesh: its per-key aggregation IS the
        # in-step psum (ICI single-host, DCN when dp spans processes) —
        # fused path proceeds, the store stays idle
    if module._updater is None:
        return "no_optimizer"
    if module.inputs_need_grad:
        return "inputs_need_grad"
    req = module._exec._grad_req
    for n in module._param_names:
        if req.get(n, "null") != "write":
            return "grad_req"
        if module._exec.grad_dict.get(n) is None:
            return "grad_req"
    opt = module._optimizer
    if opt is None or opt.fused_step_kind() is None:
        return "optimizer"
    if module._mesh is not None and _DP_AXIS not in module._mesh.axis_names:
        return "mesh_no_dp"
    return None


def _hp_signature(opt):
    """The optimizer hyperparams the fused graph folds in as constants
    (lr/wd stay live — they enter as traced vectors every step).  The
    Module rebuilds the stepper when this changes, so mutating e.g.
    ``rescale_grad`` or ``momentum`` mid-run behaves like the legacy path
    instead of silently using stale values."""
    kind = opt.fused_step_kind()
    sig = (kind, float(opt.rescale_grad),
           None if opt.clip_gradient is None else float(opt.clip_gradient))
    if kind == "sgd":
        sig += (float(opt.momentum),)
    elif kind == "adam":
        sig += (float(opt.beta1), float(opt.beta2), float(opt.epsilon))
    return sig


def _state_leaves(state):
    """Flatten one Updater state slot (None | NDArray | tuple) to a list of
    jax arrays for the jitted step."""
    if state is None:
        return []
    if isinstance(state, NDArray):
        return [state._data]
    return [s._data for s in state]


def _commit_state(state, new_leaves):
    """Write the fused step's returned state leaves back into the Updater's
    NDArrays (keeps save/load_optimizer_states working unchanged)."""
    if state is None:
        assert not new_leaves
        return
    if isinstance(state, NDArray):
        state._rebind(new_leaves[0])
        return
    for s, v in zip(state, new_leaves):
        s._rebind(v)


def _build_step_fn(graph_fn, arg_names, diff_names, const_names, kind, hp,
                   nancheck=False, health_groups=None):
    """The pure fused step: one vjp over the executor graph + the in-graph
    optimizer fold.  Closed over only static structure (names, kind, static
    hyperparams, the nancheck/health flags) so one jitted instance survives
    re-binds of the same symbol and re-traces only on new shape signatures.

    With ``nancheck`` the step also returns a scalar ``finite`` flag —
    ``all(isfinite(heads)) & all(isfinite(grads))`` reduced INSIDE the same
    donated jit, so the check adds no dispatch and no sync (the caller reads
    the flag one step later, when it has materialized for free).

    With ``health_groups`` (ISSUE 12, ``MXNET_TRAINHEALTH`` or an in-graph
    monitor) the step additionally returns the trainhealth stats pytree —
    global/per-group grad norms, param norms, update-to-weight ratios and
    per-group non-finite flags, reduced by
    ``telemetry.trainhealth.compute_step_stats`` inside the same donated
    jit: observing the step costs zero extra dispatches.  Both extras
    append to the output tuple (finite flag first), so the gate-off output
    structure stays byte-identical to a build without either feature."""
    import jax
    import jax.numpy as jnp

    from ..ops.optimizer_ops import fused_update

    def step(diff_vals, grads_in, opt_state, aux_vals, const_vals, key,
             lr_vec, wd_vec):
        # grads_in is donated purely so XLA can recycle the standing grad
        # buffers for the returned gradients
        del grads_in

        def f(dvals):
            env = dict(zip(const_names, const_vals))
            env.update(zip(diff_names, dvals))
            return graph_fn([env[n] for n in arg_names], aux_vals, key)

        heads, vjp_fn, new_aux = jax.vjp(f, diff_vals, has_aux=True)
        (grads,) = vjp_fn([jnp.ones_like(h) for h in heads])
        new_params, new_state = [], []
        for i, (w, g) in enumerate(zip(diff_vals, grads)):
            st = tuple(opt_state[i])
            # like sgd_rule: a parameter updates with momentum iff it HAS a
            # momentum slot (created when the optimizer's momentum was set),
            # so mid-run momentum edits behave exactly like the legacy path
            k = ("sgd_mom" if st else "sgd") if kind == "sgd" else kind
            new_w, new_st = fused_update(k, w, g, st,
                                         lr=lr_vec[i], wd=wd_vec[i], **hp)
            new_params.append(new_w)
            new_state.append(list(new_st))
        out = (new_params, new_state, new_aux, heads, grads)
        if nancheck:
            finite = jnp.bool_(True)
            for h in heads:
                finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(h)))
            for g in grads:
                finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
            out = out + (finite,)
        if health_groups is not None:
            from ..telemetry import trainhealth

            out = out + (trainhealth.compute_step_stats(
                heads, grads, diff_vals, new_params, health_groups),)
        return out

    return step


class FusedStepper:
    """Per-Module fused-step cache: builds the jitted step once (per
    optimizer configuration) and re-dispatches it for every eligible step;
    jax.jit's executable cache provides the per-shape-signature caching.

    With a mesh the same jit is built sharding-annotated (``out_shardings``
    pinned so params/state keep their layout across donated steps, GSPMD
    inserting the dp collectives); the jit construction is deferred to the
    first ``run`` because the ZeRO-1 ``out_shardings`` pytree needs the
    optimizer-state leaf structure, which ``Updater.states`` materializes
    lazily."""

    def __init__(self, module):
        exec_ = module._exec
        opt = module._optimizer
        self._opt = opt
        self._kind = opt.fused_step_kind()
        assert self._kind is not None
        self._hp_sig = _hp_signature(opt)
        self._arg_names = list(exec_._arg_names)
        self._aux_names = list(exec_._aux_names)
        self._diff_names = list(module._param_names)
        dset = set(self._diff_names)
        self._const_names = [n for n in self._arg_names if n not in dset]
        hp = {"rescale_grad": float(opt.rescale_grad),
              "clip_gradient": (-1.0 if opt.clip_gradient is None
                                else float(opt.clip_gradient))}
        if self._kind == "sgd":
            hp["momentum"] = float(opt.momentum)
        elif self._kind == "adam":
            hp.update(beta1=float(opt.beta1), beta2=float(opt.beta2),
                      epsilon=float(opt.epsilon))
        self._nancheck = env_flag("MXNET_NANCHECK")
        # trainhealth (ISSUE 12): in-graph stats ride the same donated jit
        # when the env gate is on OR a pattern-filtered Monitor is routed
        # onto the fused step (Module.install_monitor).  Both flip the
        # output structure, so both are stepper identity (stale() rebuilds
        # on a change) and the AOT key gains a marker — the gate-off key
        # stays byte-identical to a build without the feature.
        from ..telemetry import trainhealth

        self._health_env = trainhealth.enabled()
        self._monitor_attached = \
            getattr(module, "_stat_monitor", None) is not None
        self._health_groups = None
        self._health_verdicts = None
        if self._health_env or self._monitor_attached:
            self._health_groups = trainhealth.param_groups(self._diff_names)
            self._health_verdicts = trainhealth.group_verdict_classes(
                module, self._diff_names, self._health_groups)
        self._last_health = None  # (step number, device stats pytree)
        self._mesh = module._mesh
        self._zero = self._mesh is not None and fused_zero_enabled()
        self._donate = fused_donate_enabled()
        # the executor's bind-time graph-pass snapshot (ISSUE 7): the
        # stepper's step fn closes over the (possibly pass-optimized) train
        # plan, so the snapshot is program identity — it keys the AOT cache
        # entry and, via stale(), forces a rebuild when a re-bind (reshape)
        # lands on an executor with a different snapshot
        self._passes_on = exec_._graph_passes
        # persistent AOT executable cache (compile_cache.py, ISSUE 6): the
        # logical key is everything folded into the compiled step besides
        # argument shapes (those join at prepare time) and the environment
        # (verified inside the cache entry — incl. the mesh descriptor, so
        # a restart onto a different topology misses cleanly)
        from .. import compile_cache

        self._aot_key = None
        if compile_cache.active():
            # mesh PRESENCE is program identity (out_shardings, in-step
            # psum); mesh SHAPE lives in the verified environment
            # fingerprint, so a restart onto a different topology is a
            # clean miss + recompile rather than a different entry
            self._aot_key = (
                "fused_step",
                compile_cache.symbol_fingerprint(module._symbol),
                tuple(self._diff_names), tuple(self._const_names),
                tuple(self._aux_names), self._hp_sig, self._nancheck,
                self._zero, self._mesh is not None,
                "donate:0123" if self._donate else "donate:none")
            if self._health_groups is not None:
                # appended (not an always-present flag) so gate-off keys
                # stay byte-identical to pre-trainhealth entries
                self._aot_key = self._aot_key + ("trainhealth",)
        # symbol kept for the compile plane's logical row key (ISSUE 13) —
        # a Symbol, not an executor: no buffer pinning across re-binds
        self._symbol_ref = module._symbol
        self._nsteps = 0
        self._pending_flag = None  # (finite device scalar, step number)
        self._fn = _build_step_fn(exec_._graph_fn(True), self._arg_names,
                                  self._diff_names, self._const_names,
                                  self._kind, hp, nancheck=self._nancheck,
                                  health_groups=self._health_groups)
        self._jit = None
        self._step = None
        # mesh-path sharding cache, filled on first run (needs the state
        # leaf structure): (repl, [grad/param spec]*P, [[state leaf spec]])
        # — static for the stepper's lifetime (param shapes survive
        # retraces), so run() never rebuilds NamedShardings per step
        self._shardings = None

    @property
    def mesh(self):
        return self._mesh

    @property
    def zero(self):
        """True when this stepper runs in ZeRO-1 mode (sharded opt state)."""
        return self._zero

    # -- mesh shardings ------------------------------------------------------
    def _repl(self):
        from ..parallel import named_sharding

        return named_sharding(self._mesh)

    def _shard_spec(self, v):
        """Layout for grads and optimizer-state leaves on the mesh path:
        ZeRO-1 partitions them over dp (``parallel.zero_shard_spec``), the
        replicated mode keeps them whole on every device."""
        if not self._zero:
            return self._repl()
        from ..parallel import zero_shard_spec

        return zero_shard_spec(v, self._mesh, _DP_AXIS)

    @staticmethod
    def _place(v, sharding):
        """Commit ``v`` to ``sharding`` if it is not already there — a no-op
        from the second step on (the pinned out_shardings hand back buffers
        already in layout, so donation recycles them in place)."""
        from ..parallel import place_committed

        return place_committed(v, sharding)

    def _ensure_jit(self, diff_vals, leaves):
        """Build the jitted step on first dispatch.  Mesh path: pin
        ``out_shardings`` (params/aux replicated; grads and state leaves per
        ``_shard_spec``; heads and the nancheck flag compiler-chosen) so the
        layout survives every donated step, and declare the GSPMD-derived
        collectives to telemetry once per build."""
        import jax

        if self._step is not None:
            return
        donate = (0, 1, 2, 3) if self._donate else ()
        if self._mesh is None:
            self._jit = jax.jit(self._fn, donate_argnums=donate)
        else:
            from ..parallel import note_derived

            repl, grad_sh, state_sh = self._shardings
            out_sh = ([repl] * len(diff_vals), state_sh,
                      [repl] * len(self._aux_names), None, grad_sh)
            if self._nancheck:
                out_sh = out_sh + (None,)
            if self._health_groups is not None:
                out_sh = out_sh + (None,)  # stats pytree: compiler-chosen
            self._jit = jax.jit(self._fn, donate_argnums=donate,
                                out_shardings=out_sh)
            # declared ONCE per stepper build (not per retrace like the
            # explicit lax collectives — a reshape re-specializes the same
            # logical collectives, so one declaration per layout is honest).
            # mesh= buckets the same bytes by slowest link crossed: dcn when
            # the dp axis spans processes (pod), ici on a single host.
            if self._zero:
                # only leaves zero_shard_spec actually splits ride the
                # reduce-scatter/allgather; non-divisible leaves stay
                # replicated and their grads are a plain psum
                split = [v for v, s in zip(diff_vals, grad_sh) if s != repl]
                whole = [v for v, s in zip(diff_vals, grad_sh) if s == repl]
                note_derived("reduce_scatter", split,
                             mesh=self._mesh, axis=_DP_AXIS)
                note_derived("allgather", split,
                             mesh=self._mesh, axis=_DP_AXIS)
                note_derived("psum_grads", whole,
                             mesh=self._mesh, axis=_DP_AXIS)
            else:
                note_derived("psum_grads", diff_vals,
                             mesh=self._mesh, axis=_DP_AXIS)
        if self._aot_key is not None:
            from .. import compile_cache

            # donated=True: on the CPU backend the disk tier is skipped
            # entirely — restored donated executables compute wrong
            # trajectories there (the donation hazard, compile_cache.py
            # docstring) — so a CPU restart re-pays this compile; TPU-class
            # backends restore normally.  MXNET_FUSED_DONATE=0 makes the
            # restore legal everywhere.  Cache off ⇒ the plain jit above.
            self._jit = compile_cache.CachedFunction(
                self._jit, self._aot_key, name="fused_step",
                mesh_desc=compile_cache.mesh_descriptor(self._mesh),
                donated=self._donate, passes_on=self._passes_on)
        else:
            from ..telemetry import costplane

            if costplane.enabled():
                # compile plane (ISSUE 13): without the AOT cache the
                # donated train-step jit still records one ledger row per
                # shape signature.  donated=True: a dispatch failure
                # re-raises instead of re-invoking the jit on consumed
                # buffers (compile_cache's donation stance).
                from .. import compile_cache

                self._jit = costplane.instrument_jit(
                    self._jit, "fused_step",
                    ("fused_step",
                     compile_cache.symbol_fingerprint(self._symbol_ref),
                     tuple(self._diff_names), self._hp_sig, self._nancheck,
                     self._zero, self._mesh is not None, self._passes_on,
                     self._health_groups is not None),
                    donated=self._donate)
        # compile/steady-state accounting (identity when telemetry is off)
        self._step = telemetry.instrument_step(self._jit,
                                               name="module_fused_step")

    def cache_size(self):
        """Number of compiled executables (one per shape signature)."""
        size = getattr(self._jit, "_cache_size", None)
        return size() if size is not None else None

    def stale(self, module):
        """True when the Module's optimizer (or a folded-in hyperparam, the
        MXNET_NANCHECK gate — it changes the step's output structure — the
        MXNET_TRAINHEALTH gate / in-graph monitor attachment — same reason
        — or the MXNET_FUSED_ZERO gate — it changes the state layout)
        changed since this stepper was built — caller rebuilds."""
        from ..telemetry import trainhealth

        return (module._optimizer is not self._opt
                or _hp_signature(module._optimizer) != self._hp_sig
                or env_flag("MXNET_NANCHECK") != self._nancheck
                or trainhealth.enabled() != self._health_env
                or (getattr(module, "_stat_monitor", None) is not None)
                != self._monitor_attached
                or (module._mesh is not None
                    and fused_zero_enabled() != self._zero)
                # donation is executable identity (argnums + AOT key)
                or fused_donate_enabled() != self._donate
                # a re-bind whose executor snapshotted a different
                # MXNET_GRAPH_PASSES state: the cached step fn closes over
                # the other plan flavor — rebuild instead of mixing
                or module._exec._graph_passes != self._passes_on)

    def check_nonfinite(self):
        """Raise if the PREVIOUS step's folded isfinite flag tripped.

        The flag is an output of the fused jit, so reading it right after
        dispatch would add the per-step sync the fold exists to avoid;
        instead ``run`` checks it just before dispatching the next step, by
        which point it is long materialized (the next step consumes the
        previous outputs anyway).  The error therefore surfaces one update()
        late but NAMES the offending step."""
        if self._pending_flag is None:
            return
        flag, stepno = self._pending_flag
        self._pending_flag = None
        if not bool(flag):
            telemetry.note_nonfinite("fused")
            # black box first (ISSUE 12 satellite): the raise below ends
            # the run, so the flight recorder dumps NOW — step timeline
            # plus the last trainhealth rows, when either plane is live
            telemetry.trainhealth.note_nonfinite_trip("fused", stepno)
            raise MXNetError(
                "MXNET_NANCHECK: non-finite loss/gradient in fused train "
                "step %d (detected before step %d: the flag is folded into "
                "the fused dispatch and read one step later to avoid a "
                "per-step sync)" % (stepno, stepno + 1))

    # -- trainhealth surfaces (ISSUE 12) -------------------------------------
    def pop_health(self):
        """(step number, device stats pytree) of the last dispatched step,
        or None — consumed by ``telemetry.trainhealth.HealthPlane.drain``
        (one drain per step; a second pop returns None)."""
        h, self._last_health = self._last_health, None
        return h

    def feed_monitor(self, mon):
        """Feed an activated in-graph :class:`~mxnet_tpu.monitor.Monitor`
        the last step's stats as ``(name, value)`` rows —
        ``<group>:grad_norm`` / ``:param_norm`` / ``:update_ratio`` plus
        ``global:grad_norm`` and ``loss`` — pattern-filtered by the
        monitor itself.  Reads device scalars (a sync), but only on
        monitor-activated interval batches."""
        h = self._last_health
        if h is None or self._health_groups is None:
            return
        _stepno, stats = h
        gn = np.asarray(stats["grad_norm"])
        pn = np.asarray(stats["param_norm"])
        ur = np.asarray(stats["update_ratio"])
        for i, (group, _idxs) in enumerate(self._health_groups):
            mon.observe("%s:grad_norm" % group, gn[i])
            mon.observe("%s:param_norm" % group, pn[i])
            mon.observe("%s:update_ratio" % group, ur[i])
        mon.observe("global:grad_norm",
                    np.asarray(stats["global_grad_norm"]))
        mon.observe("loss", np.asarray(stats["loss"]))

    def run(self, module):
        """Dispatch ONE fused step over the feed already staged in the
        executor's arg buffers, then commit params / optimizer state / aux /
        outputs / grads.  Consumes exactly one RNG key (like the legacy
        forward), so seeded runs stay reproducible across paths."""
        from .. import random as _rnd

        exec_ = module._exec
        opt = self._opt
        updater = module._updater
        diff_vals = [exec_.arg_dict[n]._data for n in self._diff_names]
        grads_in = [exec_.grad_dict[n]._data for n in self._diff_names]
        const_vals = [exec_.arg_dict[n]._data for n in self._const_names]
        aux_vals = [exec_.aux_dict[n]._data for n in self._aux_names]
        states, leaves = [], []
        for i, n in enumerate(self._diff_names):
            if i not in updater.states:
                updater.states[i] = opt.create_state(i, exec_.arg_dict[n])
                updater.states_synced[i] = True
            states.append(updater.states[i])
            leaves.append(_state_leaves(updater.states[i]))
        if self._mesh is not None:
            # commit every donated operand to its pinned layout (params/aux
            # replicated over the mesh, grads + opt state per _shard_spec —
            # 1/dp shards in ZeRO-1 mode).  Only the FIRST step actually
            # moves bytes; afterwards the step's out_shardings return
            # buffers already in layout and _place is a sharding == check.
            # The batch feed itself is already dp-sharded by _stage_batch.
            if self._shardings is None:
                self._shardings = (
                    self._repl(),
                    [self._shard_spec(v) for v in diff_vals],
                    [[self._shard_spec(v) for v in lv] for lv in leaves])
            repl, grad_sh, state_sh = self._shardings
            diff_vals = [self._place(v, repl) for v in diff_vals]
            aux_vals = [self._place(v, repl) for v in aux_vals]
            grads_in = [self._place(g, s)
                        for g, s in zip(grads_in, grad_sh)]
            leaves = [[self._place(v, s) for v, s in zip(lv, shl)]
                      for lv, shl in zip(leaves, state_sh)]
        self._ensure_jit(diff_vals, leaves)
        # host-side hyperparam prep, O(P) python and zero dispatches: update
        # counts first (the legacy Updater order), then read lr/wd through
        # the optimizer's scheduler/multiplier logic; adam's bias correction
        # folds into lr so the in-graph kernel stays schedule-free
        for i in range(len(self._diff_names)):
            opt._update_count(i)
        lrs, wds = [], []
        from ..ops.optimizer_ops import adam_bias_corrected_lr

        for i in range(len(self._diff_names)):
            lr, wd = opt._get_lr(i), opt._get_wd(i)
            if self._kind == "adam":
                lr = adam_bias_corrected_lr(lr, opt._index_update_count[i],
                                            opt.beta1, opt.beta2)
            lrs.append(lr)
            wds.append(wd)
        key = _rnd.next_key()
        if self._nancheck:
            self.check_nonfinite()
        out = self._step(
            diff_vals, grads_in, leaves, aux_vals, const_vals, key,
            np.asarray(lrs, np.float32), np.asarray(wds, np.float32))
        new_params, new_state, new_aux, heads, grads = out[:5]
        extra = list(out[5:])
        self._nsteps += 1
        if self._nancheck:
            self._pending_flag = (extra.pop(0), self._nsteps)
        if self._health_groups is not None:
            # device arrays, NOT read here (that would add the per-step
            # sync the in-graph fold avoids): the fit loop drains them
            # after its metric read has already synced this dispatch
            self._last_health = (self._nsteps, extra.pop(0))
        for n, v in zip(self._diff_names, new_params):
            exec_.arg_dict[n]._rebind(v)
        for n, g in zip(self._diff_names, grads):
            exec_.grad_dict[n]._rebind(g)
        for n, v in zip(self._aux_names, new_aux):
            exec_.aux_dict[n]._rebind(v)
        for st, new_leaves in zip(states, new_state):
            _commit_state(st, new_leaves)
        exec_.outputs = [_wrap(h) for h in heads]
        exec_._last_key = key
        exec_._last_is_train = True
