"""Module — the concrete symbolic training module.

Reference ``python/mxnet/module/module.py`` (bind ``:364``, init_optimizer
``:473``, forward ``:572``, update ``:643``, save_checkpoint ``:165``).

One jit Executor replaces the reference's per-device executor group; shape
changes re-bind (re-jit) exactly like the reference's MutableModule.  Data
parallelism: pass ``mesh=`` (a ``jax.sharding.Mesh`` with a ``dp`` axis) and
every batch is sharded over it while params stay replicated — the XLA
equivalent of DataParallelExecutorGroup + kvstore 'device'
(``executor_group.py:143``, ``comm.h:451``).  An eligible mesh-fed train
step runs as ONE donated sharding-annotated jit dispatch (vjp + in-step dp
psum + optimizer, module/fused_step.py ISSUE 5; ``MXNET_FUSED_ZERO=1`` adds
ZeRO-1 optimizer-state sharding), with the legacy sharded forward kept as
the fallback for the cases the fused graph cannot express.
"""
from __future__ import annotations

import logging

from .. import ndarray as nd
from .. import optimizer as opt_mod
from .. import telemetry
from ..base import MXNetError, env_flag
from ..io import DataDesc
from ..telemetry import tracing
from ..model import (
    _create_kvstore,
    _initialize_kvstore,
    _update_params,
    _update_params_on_kvstore,
    load_checkpoint,
    save_checkpoint,
)
from .base_module import BaseModule, _check_input_names

__all__ = ["Module"]


def _as_descs(shapes):
    if shapes is None:
        return None
    out = []
    for s in shapes:
        if isinstance(s, DataDesc):
            out.append(s)
        else:
            name, shape = s[0], s[1]
            out.append(DataDesc(name, shape))
    return out


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, mesh=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._state_names = list(state_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        self._context = context
        self._mesh = mesh
        _check_input_names(symbol, self._data_names, "data", True)
        _check_input_names(symbol, self._label_names, "label", False)
        _check_input_names(symbol, self._state_names, "state", True)
        _check_input_names(symbol, self._fixed_param_names, "fixed_param", True)

        arg_names = symbol.list_arguments()
        input_names = set(self._data_names + self._label_names + self._state_names)
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._exec = None
        self._data_shapes = None
        self._label_shapes = None
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._grad_req = "write"
        # fused train-step state (ISSUE 3, module/fused_step.py): the cached
        # stepper and the staged-batch flag forward_backward hands update()
        self._fused = None
        self._fused_pending = False
        # in-graph monitor (ISSUE 12): a pattern-filtered Monitor routed
        # onto the fused step's trainhealth stats instead of the un-jitted
        # executor callback (install_monitor decides the route)
        self._stat_monitor = None
        self._nan_step = 0  # MXNET_NANCHECK legacy-path step counter
        # prefetch state (ISSUE 5): (batch_obj, feed) pre-staged by
        # prepare() so the next batch's (sharded) device_put overlaps the
        # in-flight step instead of serializing behind it
        self._prestaged = None

    # -- properties ----------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return [(n, o.shape) for n, o in zip(self._output_names, self._exec.outputs)] if self._exec.outputs else None

    # -- params ---------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        if self._fused is not None:
            # MXNET_NANCHECK reads the fused flag one step late; the natural
            # sync points (fit's epoch-end get_params, checkpointing) drain
            # the pending flag so the LAST step of a run is still checked
            self._fused.check_nonfinite()
        self._sync_params_from_exec()
        return dict(self._arg_params), dict(self._aux_params)

    def _sync_params_from_exec(self):
        if self._exec is None:
            return
        for n in self._param_names:
            self._arg_params[n] = self._exec.arg_dict[n]
        for n in self._aux_names:
            self._aux_params[n] = self._exec.aux_dict[n]

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        """Reference module.py init_params — initializer fills anything not
        supplied by arg_params/aux_params."""
        assert self.binded, "call bind before initializing the parameters"
        if self.params_initialized and not force_init:
            return
        from ..initializer import Uniform, InitDesc

        initializer = initializer if initializer is not None else Uniform(0.01)

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cached = cache[name]
                if cached is not arr:
                    if cached.shape != arr.shape:
                        raise ValueError(
                            "shape mismatch for %s: loaded %s vs expected %s"
                            % (name, cached.shape, arr.shape)
                        )
                    arr._rebind(cached._data)
            else:
                if cache is not None and not allow_missing:
                    raise RuntimeError("%s is not presented" % name)
                if initializer is not None:
                    initializer(InitDesc(name), arr)

        # Module.load pre-populates _arg_params; use them as the cache
        if arg_params is None and self._arg_params:
            arg_params = self._arg_params
            allow_missing = True
        if aux_params is None and self._aux_params:
            aux_params = self._aux_params
            allow_missing = True
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            _impl(name, arr, arg_params)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            _impl(name, arr, aux_params)

        if arg_params is not None and not allow_extra:
            for name in arg_params:
                if name not in self._param_names and name not in self._data_names + self._label_names:
                    raise ValueError("provided arg_params %s not found in symbol" % name)

        self._arg_params = {n: self._exec.arg_dict[n] for n in self._param_names}
        self._aux_params = {n: self._exec.aux_dict[n] for n in self._aux_names}
        self.params_initialized = True

    # -- bind -----------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None, grad_req="write"):
        if force_rebind:
            self._exec = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        assert not (not for_training and inputs_need_grad)

        # Under a process-spanning mesh the caller (fit's iterator contract)
        # binds with HOST-LOCAL shapes; the jitted program must see global
        # ones — every rank traces the same global computation and feeds its
        # per-host shard (parallel.global_batch_array).  Single-host meshes
        # scale by 1, keeping the descs byte-identical.
        self._data_shapes = self._global_descs(_as_descs(data_shapes))
        self._label_shapes = self._global_descs(_as_descs(label_shapes))

        shape_dict = {d.name: d.shape for d in self._data_shapes}
        if self._label_shapes:
            shape_dict.update({d.name: d.shape for d in self._label_shapes})

        arg_names = self._symbol.list_arguments()
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shape_dict)
        shape_of = dict(zip(arg_names, arg_shapes))

        args = {}
        for n in arg_names:
            if shared_module is not None and n in getattr(shared_module, "_param_names", []):
                args[n] = shared_module._exec.arg_dict[n]
            elif self._arg_params is not None and n in self._arg_params and self._arg_params[n].shape == shape_of[n]:
                args[n] = self._arg_params[n]  # survive re-bind (MutableModule)
            else:
                args[n] = nd.zeros(shape_of[n], ctx=self._context if not isinstance(self._context, list) else None)
        aux = {}
        aux_of = dict(zip(self._aux_names, aux_shapes))
        for n in self._aux_names:
            if shared_module is not None and n in getattr(shared_module, "_aux_names", []):
                aux[n] = shared_module._exec.aux_dict[n]
            elif self._aux_params is not None and n in self._aux_params and self._aux_params[n].shape == aux_of[n]:
                aux[n] = self._aux_params[n]
            else:
                aux[n] = nd.zeros(aux_of[n])

        grads = None
        req = {}
        if for_training and grad_req != "null":
            grads = {}
            for n in self._param_names:
                if n in self._fixed_param_names:
                    req[n] = "null"
                    continue
                req[n] = grad_req if isinstance(grad_req, str) else grad_req.get(n, "write")
                grads[n] = nd.zeros(shape_of[n])
            for n in self._data_names:
                if inputs_need_grad:
                    req[n] = "write"
                    grads[n] = nd.zeros(shape_of[n])
                else:
                    req[n] = "null"
            for n in self._label_names + self._state_names:
                req[n] = "null"
        else:
            req = "null"

        self._exec = self._symbol.bind(
            ctx=self._context if not isinstance(self._context, list) else None,
            args=args, args_grad=grads, grad_req=req, aux_states=aux,
        )
        self.binded = True
        self._prestaged = None  # pre-staged feed targeted the old executor

        if shared_module is not None and shared_module.params_initialized:
            self._arg_params = {n: self._exec.arg_dict[n] for n in self._param_names}
            self._aux_params = {n: self._exec.aux_dict[n] for n in self._aux_names}
            self.params_initialized = True

    def reshape(self, data_shapes, label_shapes=None):
        """Re-bind for new shapes, keeping params (reference module.py:452).
        The fused stepper survives re-binds of the same symbol — jax.jit
        re-traces once per new shape signature and caches it."""
        assert self.binded
        self._flush_pending()
        params_were_init = self.params_initialized
        self._sync_params_from_exec() if params_were_init else None
        self.bind(data_shapes, label_shapes, self.for_training, self.inputs_need_grad,
                  force_rebind=True, grad_req=self._grad_req)
        self.params_initialized = params_were_init

    # -- optimizer -------------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd", optimizer_params=None, force_init=False):
        """Reference module.py:473 — chooses kvstore-vs-local updater."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._fused is not None:  # drain any unread nancheck flag first
            self._fused.check_nonfinite()
        self._fused = None  # stepper folds optimizer hyperparams: rebuild

        kv, update_on_kvstore = _create_kvstore(
            kvstore, 1, {n: self._exec.arg_dict[n] for n in self._param_names},
            mesh=self._mesh,
        )
        # loss-op backwards emit per-sample gradients; normalize by the
        # global batch like the reference (module.py:497 rescale_grad)
        batch_size = self._data_shapes[0].shape[0]
        if kv and "dist" in kv.type:
            from ..parallel.mesh import mesh_spans_processes

            # a process-spanning mesh already bound GLOBAL shapes (bind
            # scaled the iterator-local descs), so the num_workers multiply
            # would double-count the pod's batch
            if not mesh_spans_processes(self._mesh):
                batch_size *= kv.num_workers
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params or {})
            optimizer_params.setdefault("rescale_grad", 1.0 / batch_size)
            optimizer = opt_mod.create(optimizer, **optimizer_params)
        elif optimizer.rescale_grad != 1.0 / batch_size:
            # reference module.py:523-528: a manually-built optimizer keeps
            # its own rescale_grad, but a mismatch silently mis-scales
            # gradients by the batch size — warn exactly like the reference
            import warnings

            warnings.warn(
                "Optimizer created manually outside Module but rescale_grad "
                "is %g rather than 1.0/batch_size (%g). Is this intended?"
                % (optimizer.rescale_grad, 1.0 / batch_size))
        optimizer.idx2name = {i: n for i, n in enumerate(self._param_names)}
        if hasattr(self._symbol, "attr_dict"):
            optimizer.sym_info = (self._symbol.attr_dict(), self._symbol.list_arguments())
        # repopulate name-keyed multipliers now that idx2name is known
        # (wd exemption for bias/gamma, __lr_mult__/__wd_mult__ attrs)
        optimizer.set_lr_mult(getattr(optimizer, "lr_mult", {}) or {})
        optimizer.set_wd_mult(getattr(optimizer, "wd_mult", {}) or {})

        self._optimizer = optimizer
        self._kvstore = kv
        self._update_on_kvstore = update_on_kvstore
        self._updater = None
        if kv:
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
            _initialize_kvstore(kv, [self._exec.arg_dict[n] for n in self._param_names],
                                {n: self._exec.arg_dict[n] for n in self._param_names},
                                self._param_names, update_on_kvstore)
        if not update_on_kvstore:
            self._updater = opt_mod.get_updater(optimizer)
        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    # -- compute ---------------------------------------------------------------
    def _batch_descs(self, data_batch):
        """DataDescs the batch would feed (shape-change detection)."""
        provide = getattr(data_batch, "provide_data", None)
        return _as_descs(provide) if provide else [
            DataDesc(n, a.shape) for n, a in zip(self._data_names, data_batch.data)
        ]

    def _global_descs(self, descs):
        """Scale iterator-local leading dims to the GLOBAL shapes the bound
        program uses.  Identity (factor 1) everywhere except a mesh whose dp
        axis spans processes, where each host feeds ``1/factor`` of the
        batch."""
        if not descs or self._mesh is None:
            return descs
        from ..parallel.mesh import mesh_batch_factor

        factor = mesh_batch_factor(self._mesh)
        if factor == 1:
            return descs
        return [DataDesc(d.name, (d.shape[0] * factor,) + tuple(d.shape[1:]))
                for d in descs]

    def _build_feed(self, data_batch):
        """{arg name: device-ready NDArray} for a shape-matching batch —
        under a mesh every array is committed dp-sharded here (the
        ``device_put`` the prefetch path issues early, ISSUE 5)."""
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if self._label_shapes and getattr(data_batch, "label", None) is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = arr
        elif self._label_shapes:
            # predict-mode batch without labels: keep stale label buffers
            pass
        if self._mesh is not None:
            from ..parallel import shard
            from ..parallel.mesh import global_batch_array, mesh_spans_processes

            if mesh_spans_processes(self._mesh):
                import numpy as np

                # pod mesh: this host holds only its shard of the batch —
                # assemble the global jax.Array from per-device local
                # buffers (no host gathering, tentpole contract)
                out = {}
                for k, v in feed.items():
                    arr = v.asnumpy() if isinstance(v, nd.NDArray) else np.asarray(v)
                    spec = ("dp",) + (None,) * (arr.ndim - 1)
                    out[k] = nd.NDArray(
                        global_batch_array(arr, self._mesh, spec))
                return out
            return {
                k: shard(v if isinstance(v, nd.NDArray) else nd.array(v),
                         ("dp",) + (None,) * (len(v.shape) - 1), mesh=self._mesh)
                for k, v in feed.items()
            }
        return {k: v if isinstance(v, nd.NDArray) else nd.array(v)
                for k, v in feed.items()}

    def _stage_batch(self, data_batch):
        """Reshape-on-new-batch-shape (MutableModule semantics) + write the
        batch feed into the executor's arg buffers.  Shared by ``forward``
        and the fused ``forward_backward`` staging (module/fused_step.py).
        A feed already pre-staged for this very batch by ``prepare`` is
        consumed as-is — its device_put was issued while the previous step
        was still in flight.

        Any object with a ``.data`` list is a valid batch (reference
        module.py duck-types the same way —
        example/python-howto/debug_conv.py SimpleData).
        """
        new_descs = self._batch_descs(data_batch)
        if ([d.shape for d in self._global_descs(new_descs)]
                != [d.shape for d in self._data_shapes]):
            if getattr(data_batch, "provide_label", None):
                new_labels = _as_descs(data_batch.provide_label)
            elif getattr(data_batch, "label", None) is not None and self._label_shapes:
                new_labels = [DataDesc(n, a.shape) for n, a in zip(self._label_names, data_batch.label)]
            elif self._label_shapes:
                # label-less batch (predict): rescale label batch dims to match
                new_batch = new_descs[0].shape[0]
                new_labels = [DataDesc(d.name, (new_batch,) + tuple(d.shape[1:]))
                              for d in self._label_shapes]
            else:
                new_labels = None
            self.reshape(new_descs, new_labels)

        staged = self._prestaged
        self._prestaged = None
        if staged is not None and staged[0] is data_batch:
            feed = staged[1]
        else:
            feed = self._build_feed(data_batch)
        for k, v in feed.items():
            self._exec.arg_dict[k] = v

    def prepare(self, data_batch):
        """Pre-stage the UPCOMING batch (ISSUE 5): issue its (sharded)
        host→device transfer now, while the in-flight step still occupies
        the device, so the copy overlaps compute instead of serializing at
        the next ``forward_backward``.  The fit loop calls this inside its
        ``data_wait`` accounting, keeping ``data_wait_frac`` honest about
        the hidden staging cost.  Batches whose shapes would trigger a
        reshape are left to ``_stage_batch`` (a mid-flight re-bind would
        tear down buffers the pending step output reads still need)."""
        if not (self.binded and self.params_initialized):
            return
        descs = self._batch_descs(data_batch)
        if ([d.shape for d in self._global_descs(descs)]
                != [d.shape for d in self._data_shapes]):
            self._prestaged = None
            return
        self._prestaged = (data_batch, self._build_feed(data_batch))

    def _flush_pending(self):
        """Materialize a staged fused step through the legacy path — a
        consumer asked for outputs/grads (or issued another forward) before
        ``update()`` could dispatch the fused step."""
        if not self._fused_pending:
            return
        self._fused_pending = False
        telemetry.note_fused_fallback("interleaved")
        self._exec.forward(is_train=True)
        self._exec.backward()

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self._flush_pending()
        if is_train is None:
            is_train = self.for_training
        self._stage_batch(data_batch)
        self._exec.forward(is_train=is_train)

    def forward_backward(self, data_batch):
        """Reference base_module.py:192 — plus the ISSUE 3 fused fast path:
        when eligible the batch is only STAGED here, and forward + backward
        + optimizer update execute as ONE donated jit dispatch inside
        ``update()`` (module/fused_step.py; escape hatch
        ``MXNET_MODULE_FUSED_STEP=0``, fallback conditions in
        docs/PERF_NOTES.md "Fused Module train step")."""
        assert self.binded and self.params_initialized
        self._flush_pending()
        from .fused_step import fused_ineligible_reason

        reason = fused_ineligible_reason(self)
        if reason is None:
            path = "fused_mesh" if self._mesh is not None else "fused"
            with tracing.span("forward_backward", path=path):
                self._stage_batch(data_batch)
            self._fused_pending = True
            return
        if self._stat_monitor is not None and self._exec._monitor is None:
            # the fused path can't take this Module's steps, so the
            # in-graph monitor route would observe NOTHING — fall back to
            # the pre-ISSUE-12 un-jitted executor callback (full node
            # observation at legacy speed; sticky, like a monitor always
            # was before the in-graph route existed)
            mon, self._stat_monitor = self._stat_monitor, None
            mon.install(self._exec)
        # the legacy step's own forward/backward dispatches are counted at
        # the Executor dispatch sites, the optimizer storm in model.py
        telemetry.note_fused_fallback(reason)
        with tracing.span("forward_backward", path="legacy", reason=reason):
            super().forward_backward(data_batch)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._flush_pending()
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """Apply one optimizer step (reference module.py:643).

        With a fused step staged by ``forward_backward`` this is the single
        compiled dispatch of the whole training step; otherwise the legacy
        kvstore/Updater per-parameter loop runs."""
        assert self.binded and self.params_initialized and self.optimizer_initialized
        if self._fused_pending:
            self._fused_pending = False
            from .fused_step import FusedStepper, fused_zero_enabled

            if self._mesh is not None:
                span_kw = {"path": "fused_mesh",
                           "zero": int(fused_zero_enabled())}
            else:
                span_kw = {"path": "fused"}
            with tracing.span("update", **span_kw):
                if self._fused is not None and self._fused.stale(self):
                    # don't let a rebuild discard an unread nancheck flag
                    self._fused.check_nonfinite()
                    self._fused = None
                if self._fused is None:
                    self._fused = FusedStepper(self)
                self._fused.run(self)
            if self._stat_monitor is not None \
                    and getattr(self._stat_monitor, "activated", False):
                # in-graph monitor route (install_monitor): feed this
                # step's stats rows, pattern-filtered by the monitor
                self._fused.feed_monitor(self._stat_monitor)
            telemetry.note_train_step(span_kw["path"])
            telemetry.note_dispatch(1, path=span_kw["path"])
            return
        telemetry.note_train_step("legacy")
        if env_flag("MXNET_NANCHECK"):
            self._nancheck_legacy()
        with tracing.span("update", path="legacy"):
            param_arrays = [self._exec.arg_dict[n] for n in self._param_names]
            grad_arrays = [self._exec.grad_dict.get(n)
                           for n in self._param_names]
            if self._kvstore and self._update_on_kvstore:
                _update_params_on_kvstore(param_arrays, grad_arrays,
                                          self._kvstore, self._param_names)
            else:
                _update_params(param_arrays, grad_arrays, self._updater, 1,
                               kvstore=self._kvstore,
                               param_names=self._param_names)

    def _nancheck_legacy(self):
        """Opt-in ``MXNET_NANCHECK`` guard for the legacy step: verify the
        loss heads and parameter gradients are finite BEFORE the optimizer
        writes them into the weights.  The legacy path already syncs per
        dispatch, so the device readbacks here cost noise; the fused path
        folds the same check into its one dispatch (module/fused_step.py)."""
        import jax.numpy as jnp

        self._nan_step += 1
        bad = []
        for name, o in zip(self._output_names, self._exec.outputs):
            if not bool(jnp.all(jnp.isfinite(o._data))):
                bad.append("output:%s" % name)
        for n in self._param_names:
            g = self._exec.grad_dict.get(n)
            if g is not None and not bool(jnp.all(jnp.isfinite(g._data))):
                bad.append("grad:%s" % n)
        if bad:
            telemetry.note_nonfinite("legacy")
            telemetry.trainhealth.note_nonfinite_trip(
                "legacy", self._nan_step, detail=", ".join(bad[:8]))
            raise MXNetError(
                "MXNET_NANCHECK: non-finite values at train step %d: %s"
                % (self._nan_step, ", ".join(bad[:8])))

    def get_outputs(self, merge_multi_context=True):
        assert self.binded
        self._flush_pending()
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        self._flush_pending()
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        outputs = self.get_outputs()
        if self._mesh is not None:
            from ..parallel.mesh import host_local_rows, mesh_spans_processes

            if mesh_spans_processes(self._mesh):
                # pod mesh: outputs are global arrays whose rows span other
                # hosts — score THIS host's block against its local labels
                # (per-worker metrics, the reference dist_sync semantics)
                outputs = [nd.array(host_local_rows(o._data))
                           for o in outputs]
        eval_metric.update(labels, outputs)

    def trainer_stats(self):
        """The PROCESS's last drained trainhealth row (host floats:
        global/per-group grad norms, update ratios, non-finite census) or
        None — ``MXNET_TRAINHEALTH`` off, or nothing drained yet.  The
        health plane is one per process, like the flight recorder: with
        several Modules training in one process this returns whichever
        drained last.  The same block is mirrored on the ops server's
        ``/statusz`` (docs/OBSERVABILITY.md "Training health")."""
        from ..telemetry import trainhealth

        return trainhealth.trainer_stats()

    def get_states(self, merge_multi_context=True):
        assert self.binded
        return [self._exec.arg_dict[n] for n in self._state_names]

    def set_states(self, states=None, value=None):
        assert self.binded
        if states is not None:
            for n, v in zip(self._state_names, states):
                self._exec.arg_dict[n] = v if isinstance(v, nd.NDArray) else nd.array(v)
        else:
            for n in self._state_names:
                self._exec.arg_dict[n][:] = value

    def install_monitor(self, mon):
        """Attach a :class:`~mxnet_tpu.monitor.Monitor` (ISSUE 12 routing).

        ``monitor_all=False`` (default) rides the **fused step**: the
        monitor observes the in-graph trainhealth stats — per-group
        grad/param norms and update ratios, pattern-filtered by its regex
        — and training keeps its one-donated-dispatch step.
        ``monitor_all=True`` is the escape hatch: the executor's un-jitted
        per-node callback (every node output + inputs), which forces the
        legacy path — full observability at legacy speed (the reference
        semantics, and the only route that sees intermediate tensors).
        A monitor is never silently blind: one whose pattern matches NO
        in-graph stat row (it targets tensor names like ``fc1_weight``)
        takes the un-jitted route directly, and a Module whose steps turn
        out fused-INELIGIBLE for another reason (optimizer, grad_req,
        kvstore, ...) re-routes at its first legacy ``forward_backward``."""
        assert self.binded
        from ..telemetry import trainhealth
        from .fused_step import fused_enabled

        matcher = getattr(mon, "re_prog", None)
        matches_stats = matcher is None or any(
            matcher.match(n)
            for n in trainhealth.monitor_row_names(self._param_names))
        if getattr(mon, "monitor_all", False) or not fused_enabled() \
                or not matches_stats:
            self._flush_pending()  # a monitor makes future steps legacy
            self._stat_monitor = None
            mon.install(self._exec)
            return
        # in-graph route: the stepper rebuilds with health stats on its
        # next update() (stale() keys on monitor attachment)
        self._stat_monitor = mon

    # -- checkpointing ----------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """symbol json + params + optional optimizer states (reference
        module.py:165)."""
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as f:
                f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())
