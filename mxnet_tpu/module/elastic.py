"""Elastic fit-loop controller (ISSUE 20) — durable checkpoints plus the
straggler checkpoint-and-rejoin response, lifted into ``BaseModule.fit``.

The failure story this closes (ROADMAP item 2, SURVEY §5.3): the
reference survived worker churn because the parameter server held the
authoritative state and a relaunched worker pulled it back
(``is_recovery`` in ps-lite).  Under the fused pod path there is no
server — state lives sharded across every rank inside one GSPMD
program, so a single slow or dead rank stalls the whole fleet inside a
collective.  The controller turns both failure modes into bounded,
observable events:

* **rank death → fail-fast → resume.**  Every rank writes a durable
  orbax checkpoint every ``MXNET_ELASTIC_SAVE_STEPS`` global steps
  (collective, sharded, rotated).  When rank 0's podplane detector
  presumes a rank dead (push age past ``death_age_s``), the incident
  rides push responses to every surviving rank and ``after_step``
  raises — crashing out of a doomed collective beats hanging in it.
  The relaunch calls ``resume`` before the first step: the latest
  durable checkpoint reshards onto the (possibly different) mesh via
  ``CheckpointManager.restore(like=...)`` and fit fast-forwards the
  data iterator to the restored global step.

* **straggler → checkpoint-and-rejoin.**  A straggler incident carries
  ``rejoin_step`` (fleet head + ``MXNET_ELASTIC_REJOIN_MARGIN``), a
  step boundary every lockstepped rank still has ahead of it.  Each
  rank, on reaching it, force-saves the durable checkpoint, waits for
  commit, restores it back and rebinds — a value-preserving rebase
  through durable storage.  Parity holds (restore returns the exact
  bytes just saved); what the fleet gains is a guaranteed-fresh
  recovery point plus one agreed boundary where a relaunched or
  recovered rank can rejoin, instead of silently stalling the
  collective for the straggler's whole lag.

Gate: ``MXNET_ELASTIC_DIR`` unset ⇒ :func:`controller` returns None and
fit runs the unchanged loop (one env read — the planes idiom).
"""
from __future__ import annotations

import logging
import os

__all__ = ["controller", "ElasticController", "save_interval_steps",
           "max_to_keep"]


def _env_int(name, default, minimum=1):
    try:
        v = int(os.environ.get(name, "") or default)
    except ValueError:
        v = default
    return max(minimum, v)


def save_interval_steps():
    """``MXNET_ELASTIC_SAVE_STEPS`` (default 50): durable-checkpoint
    interval in global steps.  Collective + async (orbax overlaps the
    commit with training), so the steady-state cost is the device→host
    shard copy."""
    return _env_int("MXNET_ELASTIC_SAVE_STEPS", 50)


def max_to_keep():
    """``MXNET_ELASTIC_KEEP`` (default 3): checkpoints retained."""
    return _env_int("MXNET_ELASTIC_KEEP", 3)


def controller():
    """→ :class:`ElasticController` when ``MXNET_ELASTIC_DIR`` is set,
    else None."""
    path = (os.environ.get("MXNET_ELASTIC_DIR") or "").strip()
    if not path:
        return None
    return ElasticController(path)


class ElasticController:
    """One fit loop's durable-checkpoint + rejoin state machine.

    The checkpoint tree covers exactly what a mid-training restart
    needs: every trainable param, every aux, and the
    Updater's optimizer-state leaves, keyed by the fused step's
    parameter order (``module._param_names``) so a restore commits back
    through the same ``_rebind``/``_commit_state`` seams the fused step
    itself uses.  Checkpoint step indices are *global step counts*
    (completed steps since epoch 0) — identical on every rank under the
    fused path's lockstep, which is what makes the orbax save
    collective-safe.
    """

    def __init__(self, directory):
        from ..parallel.checkpoint import CheckpointManager

        self._dir = os.path.abspath(directory)
        self._mgr = CheckpointManager(self._dir, max_to_keep=max_to_keep(),
                                      save_interval_steps=save_interval_steps())
        self._log = logging.getLogger("mxnet_tpu.elastic")
        self._rejoin_step = None
        self._rejoin_incident = None
        self.resume_step = 0
        self.rejoins = 0
        self.last_rejoin_step = None
        self.saves = 0

    # -- state tree ----------------------------------------------------------
    def _tree(self, module):
        from .fused_step import _state_leaves

        exec_ = module._exec
        tree = {"arg": {n: exec_.arg_dict[n]._data
                        for n in module._param_names}}
        if module._aux_names:
            # empty subtrees are pruned (orbax rejects empty containers);
            # models without aux state (no BN) just have no "aux" key
            tree["aux"] = {n: exec_.aux_dict[n]._data
                           for n in module._aux_names}
        upd = getattr(module, "_updater", None)
        if upd is not None and getattr(upd, "states", None):
            opt = {}
            for i, st in upd.states.items():
                leaves = _state_leaves(st)
                if leaves:
                    opt[str(i)] = leaves
            if opt:
                tree["opt"] = opt
        return tree

    def _commit(self, module, restored):
        from .fused_step import _commit_state

        exec_ = module._exec
        for n in module._param_names:
            exec_.arg_dict[n]._rebind(restored["arg"][n])
        for n in module._aux_names:
            exec_.aux_dict[n]._rebind(restored["aux"][n])
        upd = getattr(module, "_updater", None)
        for key, leaves in (restored.get("opt") or {}).items():
            _commit_state(upd.states[int(key)], list(leaves))

    def _materialize_opt(self, module):
        """Create the Updater's lazy optimizer-state slots before building
        the ``like`` tree — a just-initialized module hasn't run a step
        yet, but the checkpoint being restored has ``opt`` entries and
        ``StandardRestore`` needs matching structure.  Mirrors the fused
        step's own lazy materialization (same index = ``_param_names``
        order), including the mesh layout: under a mesh the fresh leaves
        are committed to the exact sharding the fused step pins (ZeRO-1
        1/dp shards when ``MXNET_FUSED_ZERO`` is on, else replicated), so
        the orbax restore reshards straight onto process-spanning global
        arrays and the first step's ``_place`` is a no-op."""
        upd = getattr(module, "_updater", None)
        opt = getattr(module, "_optimizer", None)
        names = getattr(module, "_param_names", None)
        if upd is None or opt is None or not names:
            return
        exec_ = module._exec
        mesh = getattr(module, "_mesh", None)
        place = None
        if mesh is not None:
            import jax

            from ..parallel import zero_shard_spec
            from ..parallel.mesh import named_sharding
            from .fused_step import fused_zero_enabled

            zero = fused_zero_enabled()

            def place(leaf):
                import numpy as np

                host = np.asarray(leaf._data)
                sh = (zero_shard_spec(host, mesh) if zero
                      else named_sharding(mesh))
                # make_array_from_callback: correct on single-host AND
                # process-spanning meshes (each process materializes only
                # its addressable shards)
                arr = jax.make_array_from_callback(
                    host.shape, sh, lambda idx: host[idx])
                return type(leaf)(arr)
        for i, n in enumerate(names):
            if i not in upd.states:
                st = opt.create_state(i, exec_.arg_dict[n])
                if place is not None and st is not None:
                    if isinstance(st, (tuple, list)):
                        st = type(st)(place(leaf) for leaf in st)
                    else:
                        st = place(st)
                upd.states[i] = st
                upd.states_synced[i] = True

    def _globalize_params(self, module):
        """Under a mesh, commit every param/aux to the replicated global
        layout BEFORE the ``like`` tree is built.  ``resume`` runs right
        after ``init_params``, when the buffers are still host arrays —
        a ``like`` without shardings would make orbax restore committed
        single-device arrays, which the fused step cannot legally
        ``device_put`` onto a process-spanning mesh.  Globalizing first
        means the restore reshards straight onto the mesh and the first
        step's ``_place`` is a sharding == no-op."""
        mesh = getattr(module, "_mesh", None)
        if mesh is None:
            return
        import jax
        import numpy as np

        from ..parallel.mesh import named_sharding

        repl = named_sharding(mesh)
        exec_ = module._exec

        def _fix(nd):
            v = nd._data
            if getattr(v, "sharding", None) == repl:
                return
            if hasattr(v, "is_fully_addressable") and \
                    not v.is_fully_addressable:
                return  # already global in some other layout: leave it
            host = np.asarray(v)
            nd._rebind(jax.make_array_from_callback(
                host.shape, repl, lambda idx: host[idx]))

        for n in module._param_names:
            _fix(exec_.arg_dict[n])
        for n in module._aux_names:
            _fix(exec_.aux_dict[n])

    # -- lifecycle -----------------------------------------------------------
    def resume(self, module):
        """Restore the latest durable checkpoint into the bound module →
        the global step to resume from (0 = fresh start).  Restoring via
        ``like=`` reshards onto the module's current mesh, so a relaunch
        on a different topology comes back correct or fails loudly on a
        real shape mismatch — never a silent misassignment."""
        step = self._mgr.latest_step()
        if step is None:
            return 0
        self._globalize_params(module)
        self._materialize_opt(module)
        like = self._tree(module)
        restored = self._mgr.restore(step=step, like=like)
        self._commit(module, restored)
        self.resume_step = int(step)
        self._log.warning(
            "elastic: resumed from durable checkpoint %s at global step %d",
            self._dir, self.resume_step)
        return self.resume_step

    def after_step(self, module, global_step, pod=None):
        """Step-boundary hook (``global_step`` = completed steps).  Order
        matters: consume incidents first (a rejoin order must not be
        deferred behind a periodic save), then execute a due rejoin,
        else let the manager's ``save_interval_steps`` decide on the
        periodic save.  Returns True iff a rejoin rebase ran at this
        boundary."""
        if pod is not None and self._rejoin_step is None:
            inc = pod.pending_rejoin()
            if inc is not None:
                if inc.get("reason") == "rank_death":
                    # fail-fast: the dead rank can't join a collective
                    # save, and the next fused step would hang on it.
                    # The durable checkpoint already on disk is the
                    # recovery point for the relaunch.
                    raise RuntimeError(
                        "elastic: rank %s presumed dead (incident %s); "
                        "failing fast — relaunch resumes from durable "
                        "checkpoint step %s in %s"
                        % (inc.get("rank"), inc.get("id"),
                           self._mgr.latest_step(), self._dir))
                self._rejoin_step = int(inc["meta"]["rejoin_step"])
                self._rejoin_incident = inc.get("id")
                if self._rejoin_step <= global_step:
                    # observed past the agreed boundary (possible only if
                    # lockstep was broken, e.g. single-process tests):
                    # rebase at the very next boundary instead
                    self._rejoin_step = global_step + 1
                self._log.warning(
                    "elastic: straggler incident %s (rank %s, lag %s) — "
                    "checkpoint-and-rejoin at global step %d",
                    inc.get("id"), inc.get("rank"),
                    (inc.get("meta") or {}).get("lag_steps"),
                    self._rejoin_step)
        if self._rejoin_step is not None and global_step >= self._rejoin_step:
            # every rank passes this same agreed boundary (lockstep keeps
            # the fleet within one step), so the step index below is
            # identical fleet-wide — the collective-save requirement
            step = self._rejoin_step
            self._rejoin_step = None
            tree = self._tree(module)
            self._mgr.save(step, tree, force=True)
            self._mgr.wait_until_finished()
            self._commit(module, self._mgr.restore(step=step, like=tree))
            self.rejoins += 1
            self.last_rejoin_step = step
            self.saves += 1
            self._log.warning(
                "elastic: rejoined from durable checkpoint at global step "
                "%d (incident %s)", step, self._rejoin_incident)
            return True
        if self._mgr.save(global_step, self._tree(module)):
            self.saves += 1
        return False

    def stats(self):
        return {"dir": self._dir, "resume_step": self.resume_step,
                "rejoins": self.rejoins,
                "last_rejoin_step": self.last_rejoin_step,
                "saves": self.saves, "steps": self._mgr.all_steps()}

    def close(self):
        try:
            self._mgr.wait_until_finished()
        finally:
            self._mgr.close()
