"""BucketingModule — variable-length sequence training.

Reference ``python/mxnet/module/bucketing_module.py``: one Module per bucket
key, parameters shared across buckets.  On TPU each bucket is one jit shape
signature — switching buckets hits the compile cache instead of re-binding
executors (SURVEY §7.3 MutableModule/bucketing note).
"""
from __future__ import annotations

import logging

from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False
        self._grad_req = "write"
        self._monitor = None
        self._opt_module = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        return self._call_sym_gen(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        return self._call_sym_gen(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    def _call_sym_gen(self, bucket_key):
        return self._sym_gen(bucket_key)

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._call_sym_gen(bucket_key)
        return Module(sym, data_names, label_names, logger=self.logger,
                      context=self._context, fixed_param_names=self._fixed_param_names,
                      state_names=self._state_names)

    # -- params ----------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        self._curr_module._sync_params_from_exec()
        return self._curr_module.get_params()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(initializer=initializer, arg_params=arg_params,
                                      aux_params=aux_params, allow_missing=allow_missing,
                                      force_init=force_init, allow_extra=allow_extra)
        self.params_initialized = True

    # -- bind / bucket switching -------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None, grad_req="write"):
        if force_rebind:
            self._buckets = {}
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        assert shared_module is None
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=False, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Reference bucketing_module.py switch_bucket — share params with the
        default-bucket module."""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self._curr_module.for_training,
                        self._curr_module.inputs_need_grad, force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key],
                        grad_req=self._grad_req)
            if self._monitor is not None:
                module.install_monitor(self._monitor)
            if self.optimizer_initialized:
                self._borrow_optimizer(module)
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def prepare(self, data_batch):
        """Pre-build the upcoming batch's bucket module, then restore the
        current one (reference bucketing_module.py prepare)."""
        prev = self._curr_bucket_key
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data, data_batch.provide_label)
        self._curr_module = self._buckets[prev]
        self._curr_bucket_key = prev

    # -- optimizer / compute ------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd", optimizer_params=None, force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params, force_init=force_init)
        self._opt_module = self._curr_module
        self.optimizer_initialized = True
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                self._borrow_optimizer(mod)

    def _borrow_optimizer(self, module):
        """Share the default bucket's optimizer state (reference
        bucketing_module.py borrow_optimizer)."""
        src = self._opt_module
        module._optimizer = src._optimizer
        module._kvstore = src._kvstore
        module._update_on_kvstore = src._update_on_kvstore
        module._updater = src._updater
        module.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data, data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        # params are shared NDArrays; updating through the current module
        # updates every bucket
        self._params_dirty = True
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def get_states(self, merge_multi_context=True):
        return self._curr_module.get_states(merge_multi_context)

    def set_states(self, states=None, value=None):
        self._curr_module.set_states(states, value)

    def install_monitor(self, mon):
        assert self.binded
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)
