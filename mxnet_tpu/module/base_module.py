"""BaseModule — the abstract training-loop interface.

Reference ``python/mxnet/module/base_module.py``: the intermediate-level API
(forward/backward/update) and the high-level ``fit``/``score``/``predict``
loops (``:192-979``).  Concrete state (binding, params, optimizer) lives in
subclasses.
"""
from __future__ import annotations

import logging
import time

from .. import metric as metric_mod
from .. import ndarray as nd
from ..model import BatchEndParam
from ..telemetry import tracing

__all__ = ["BaseModule"]


def _check_input_names(symbol, names, typename, throw):
    args = set(symbol.list_arguments() + symbol.list_auxiliary_states())
    for name in names:
        if name not in args:
            msg = "You created Module with Module(..., %s_names=%s) but input with name '%s' is not found in symbol.list_arguments()." % (
                typename, str(list(names)), name
            )
            if throw:
                raise ValueError(msg)
            logging.warning(msg)


def _as_metric(m):
    return m if isinstance(m, metric_mod.EvalMetric) else metric_mod.create(m)


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- properties subclasses must provide ---------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    # -- abstract core -------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None, grad_req="write"):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd", optimizer_params=None, force_init=False):
        raise NotImplementedError()

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    # -- shared conveniences -------------------------------------------------
    def forward_backward(self, data_batch):
        """Reference base_module.py:192 — the legacy two-dispatch step.
        ``Module`` overrides this with the fused-step staging fast path
        (module/fused_step.py): when eligible, forward+backward+update run
        as one donated jit dispatch inside ``update()``; the ``fit`` loop
        below drives either path identically."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def set_params(self, arg_params, aux_params, allow_missing=False, force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname):
        save_dict = nd.load(fname)
        arg_params, aux_params = {}, {}
        for k, v in save_dict.items():
            tp, name = k.split(":", 1)
            if tp == "arg":
                arg_params[name] = v
            elif tp == "aux":
                aux_params[name] = v
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def score(self, eval_data, eval_metric, num_batch=None, batch_end_callback=None,
              score_end_callback=None, reset=True, epoch=0):
        """Evaluate over ``eval_data`` (reference base_module.py:~575)."""
        assert self.binded and self.params_initialized
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch, eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(param)
        if score_end_callback is not None:
            param = BatchEndParam(epoch=epoch, nbatch=nbatch, eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(param)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0 : out.shape[0] - pad] for out in self.get_outputs()]
            yield outputs, nbatch, eval_batch

    def predict(self, eval_data, num_batch=None, merge_batches=True, reset=True, always_output_list=False):
        """Reference base_module.py predict — collects (and optionally
        concatenates) forward outputs."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0 : out.shape[0] - pad].copy() for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, "Cannot merge batches: different numbers of outputs"
            output_list2 = [
                nd.concatenate([out[i] for out in output_list]) for i in range(num_outputs)
            ]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None, monitor=None):
        """The reference training loop (base_module.py:399), verbatim flow:
        bind → init_params → init_optimizer → per-epoch forward_backward /
        update / metric / checkpoints."""
        assert num_epoch is not None, "please specify number of epochs"
        from ..initializer import Uniform

        initializer = initializer if initializer is not None else Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer, optimizer_params=optimizer_params)

        if validation_metric is None:
            validation_metric = eval_metric
        eval_metric = _as_metric(eval_metric)

        # telemetry probe (None when MXNET_TELEMETRY is off — the loop below
        # then takes no timestamps beyond the reference's own epoch timer)
        from .. import telemetry

        probe = telemetry.step_probe("module_fit")
        # live ops plane (ISSUE 10): /metrics-scrapeable training jobs
        # (MXNET_OPS_PORT) and per-step flight-recorder events
        # (MXNET_FLIGHTREC_DIR).  Both gates unset = two env reads here
        # and an unchanged loop below (frec is None, tested).
        telemetry.ops_server.maybe_start()
        frec = telemetry.flightrec.recorder()
        # training health plane (ISSUE 12, MXNET_TRAINHEALTH): drains the
        # fused step's in-graph stats pytree once per batch — after the
        # metric read has already synced the dispatch, so the drain adds
        # no device round trip.  Gate unset = one env read here, None.
        health = telemetry.trainhealth.plane()
        # pod observability plane (ISSUE 19, MXNET_POD_METRICS): each
        # batch feeds the rank's mergeable step histogram and (throttled)
        # pushes a snapshot to rank 0.  Gate unset = one env read, None.
        pod = telemetry.podplane.plane()
        # elastic durable checkpoints + straggler checkpoint-and-rejoin
        # (ISSUE 20, MXNET_ELASTIC_DIR): periodic collective orbax saves,
        # resume-and-fast-forward on relaunch, and the podplane incident
        # response.  Needs the executor/updater seams, so only Module-like
        # subclasses participate.  Gate unset = one env read, None.
        from .elastic import controller as _elastic_controller

        elastic = (_elastic_controller()
                   if getattr(self, "_exec", None) is not None else None)
        global_step = 0
        resume_step = elastic.resume(self) if elastic is not None else 0

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            end_of_batch = False
            t0 = time.perf_counter() if probe else 0.0
            next_data_batch = next(data_iter)
            if probe:
                probe.record_data_wait(time.perf_counter() - t0)
            while not end_of_batch:
                data_batch = next_data_batch
                if global_step < resume_step:
                    # fast-forward: this step already ran before the
                    # restart (its effect is inside the restored durable
                    # checkpoint) — advance the deterministic iterator
                    # without recomputing, so the resumed run sees the
                    # same batch at the same global step as the original
                    try:
                        next_data_batch = next(data_iter)
                    except StopIteration:
                        end_of_batch = True
                    global_step += 1
                    nbatch += 1
                    continue
                t_batch = (time.perf_counter()
                           if probe or frec is not None
                           or pod is not None else 0.0)
                if monitor is not None:
                    monitor.tic()
                # span tracing (MXNET_TRACE): each batch is its own sampled
                # trace; Module's forward_backward/update spans (and kvstore
                # push/pull, Predictor dispatch below them) nest under it
                # via the thread-local current span.  Off ⇒ NULL_SPAN, no
                # hook beyond the env check — same contract as `probe`.
                step_sp = tracing.start_trace("step", epoch=epoch,
                                              step=nbatch)
                with step_sp:
                    self.forward_backward(data_batch)
                    self.update()
                    wait = 0.0
                    try:
                        t0 = time.perf_counter() if probe else 0.0
                        with tracing.span("data_wait"):
                            next_data_batch = next(data_iter)
                            # prepare() pre-stages batch N+1 — under a mesh
                            # it issues the sharded device_put now, while
                            # step N is still in flight (Module.prepare,
                            # ISSUE 5).  It runs INSIDE the data_wait span
                            # and probe window so the staging cost it hides
                            # stays visible in data_wait_frac.
                            self.prepare(next_data_batch)
                        if probe:
                            wait = time.perf_counter() - t0
                    except StopIteration:
                        end_of_batch = True
                    # the metric read syncs the async dispatch, so the batch
                    # wall time measured around it is honest device+host time
                    with tracing.span("update_metric"):
                        self.update_metric(eval_metric, data_batch.label)
                if probe:
                    probe.record_data_wait(wait)
                    probe.record_step(
                        time.perf_counter() - t_batch - wait,
                        nsamples=data_batch.data[0].shape[0])
                if frec is not None:
                    # step event (data wait included): the training-side
                    # timeline for a post-mortem dump
                    frec.record("step", dur_s=time.perf_counter() - t_batch,
                                epoch=epoch, step=nbatch)
                if health is not None:
                    health.drain(self, epoch=epoch, step=nbatch)
                if pod is not None:
                    pod.note_step(time.perf_counter() - t_batch)
                global_step += 1
                if elastic is not None:
                    # step-boundary hook: periodic durable save, straggler
                    # checkpoint-and-rejoin, rank-death fail-fast
                    elastic.after_step(self, global_step, pod)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch, eval_metric=eval_metric, locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(param)
                nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
                if probe:
                    probe.record_metric(name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, time.time() - tic)
            if probe:
                probe.epoch_event(epoch, nbatch=nbatch,
                                  seconds=round(time.time() - tic, 3))

            arg_params_, aux_params_ = self.get_params()
            self.set_params(arg_params_, aux_params_)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_params_, aux_params_)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback, epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)

            train_data.reset()

        if elastic is not None:
            # last-step durable save (force: the interval would usually
            # skip it) so a relaunch after normal completion fast-forwards
            # the whole run instead of retraining the tail
            if elastic._mgr.latest_step() != global_step:
                elastic._mgr.save(global_step, elastic._tree(self),
                                  force=True)
                elastic.saves += 1
            self._elastic_stats = elastic.stats()
            elastic.close()

    def elastic_stats(self):
        """The elastic controller's summary from the last ``fit`` run
        (ISSUE 20) — ``{dir, resume_step, rejoins, last_rejoin_step,
        saves, steps}``; None before fit or with ``MXNET_ELASTIC_DIR``
        unset."""
        return getattr(self, "_elastic_stats", None)

    # -- misc hooks ----------------------------------------------------------
    def prepare(self, data_batch):
        """Hook called with the upcoming batch (bucketing switches here)."""
        pass

    def install_monitor(self, mon):
        raise NotImplementedError()

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return []

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized


def _as_list(obj):
    if obj is None:
        return []
    return obj if isinstance(obj, (list, tuple)) else [obj]
