"""Module package — the symbolic training API.

Reference ``python/mxnet/module/``: BaseModule.fit drives the whole reference
training loop (``base_module.py:399``); Module binds a symbol into executors
(``module.py:364``); BucketingModule handles variable-length sequences.

TPU-native redesign: the reference's ``DataParallelExecutorGroup`` (one
executor per GPU, host-side batch slicing, ``executor_group.py:143``) is
replaced by ONE jit executor whose arrays can be sharded over a
``jax.sharding`` mesh — data parallelism is a sharding annotation, not an
executor list.  Shape changes re-jit under a shape-signature cache, which is
exactly the reference's bucketing/MutableModule re-bind semantics.
"""
from .base_module import BaseModule
from .module import Module
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule
from .python_module import PythonModule, PythonLossModule

__all__ = [
    "BaseModule",
    "Module",
    "BucketingModule",
    "SequentialModule",
    "PythonModule",
    "PythonLossModule",
]
