"""SequentialModule — chain modules, feeding outputs to the next's inputs.

Reference ``python/mxnet/module/sequential_module.py``.
"""
from __future__ import annotations

import logging

from .. import ndarray as nd
from ..io import DataDesc, DataBatch
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self._data_shapes = None
        self._meta_keys = {self.META_TAKE_LABELS, self.META_AUTO_WIRING}

    def add(self, module, **kwargs):
        self._modules.append(module)
        for key in kwargs:
            assert key in self._meta_keys, "Unknown meta %s (known: %s)" % (key, self._meta_keys)
        self._metas.append(kwargs)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    @property
    def data_names(self):
        assert self._modules
        return self._modules[0].data_names

    @property
    def output_names(self):
        assert self._modules
        return self._modules[-1].output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        for module in self._modules:
            module.init_params(initializer=initializer, arg_params=arg_params,
                               aux_params=aux_params, allow_missing=True,
                               force_init=force_init, allow_extra=True)

        # make sure we do not have duplicated parameter names
        def _check_name(known, new_names, modules, i):
            for name in new_names:
                assert name not in known, "Duplicated parameter names: %s in module %d" % (name, i)
                known[name] = i

        arg_names, aux_names = {}, {}
        for i_layer, module in enumerate(self._modules):
            arg, aux = module.get_params()
            _check_name(arg_names, arg.keys(), self._modules, i_layer)
            _check_name(aux_names, aux.keys(), self._modules, i_layer)
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        assert shared_module is None, "shared_module is not supported"
        assert len(self._modules) > 0
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        my_data_shapes = data_shapes
        my_label_shapes = label_shapes
        anybody_ever_needs_label = False
        for i_layer, module in enumerate(self._modules):
            meta = self._metas[i_layer]
            if meta.get(self.META_TAKE_LABELS):
                module.bind(my_data_shapes, label_shapes, for_training,
                            inputs_need_grad or i_layer > 0, force_rebind, None, grad_req)
                anybody_ever_needs_label = True
            else:
                module.bind(my_data_shapes, None, for_training,
                            inputs_need_grad or i_layer > 0, force_rebind, None, grad_req)
            # wire outputs → next inputs
            if i_layer < len(self._modules) - 1:
                nxt = self._modules[i_layer + 1]
                if self._metas[i_layer + 1].get(self.META_AUTO_WIRING, True):
                    data_names = nxt.data_names
                    if module.symbol is not None:
                        shape_dict = {
                            (d.name if isinstance(d, DataDesc) else d[0]):
                            (d.shape if isinstance(d, DataDesc) else d[1])
                            for d in my_data_shapes
                        }
                        _, out_shapes, _ = module.symbol.infer_shape_partial(**shape_dict)
                    else:  # PythonModule et al: already bound, shapes known
                        out_shapes = [s for _, s in module.output_shapes]
                    assert len(data_names) == len(out_shapes)
                    my_data_shapes = [DataDesc(n, s) for n, s in zip(data_names, out_shapes)]
        if not anybody_ever_needs_label:
            self._label_shapes = None
        else:
            self._label_shapes = label_shapes
        self._data_shapes = data_shapes
        self.binded = True

    def init_optimizer(self, kvstore="local", optimizer="sgd", optimizer_params=None, force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params, force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        batch = DataBatch(data=data_batch.data, label=data_batch.label,
                          pad=data_batch.pad, provide_data=data_batch.provide_data,
                          provide_label=data_batch.provide_label)
        for i_layer, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if i_layer == len(self._modules) - 1:
                break
            batch = DataBatch(data=module.get_outputs(), label=data_batch.label, pad=data_batch.pad)
        return

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i_layer in range(len(self._modules) - 1, -1, -1):
            module = self._modules[i_layer]
            module.backward(out_grads=out_grads)
            if i_layer == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized and self.optimizer_initialized
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        for module, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for module in self._modules:
            module.install_monitor(mon)
