"""Deployment inference API — the reference's C predict API, TPU-native.

The reference ships a deployment-only ABI (`include/mxnet/c_predict_api.h`,
`src/c_api/c_predict_api.cc`): create a predictor from a symbol JSON + a
param blob, feed inputs, run forward, read outputs — no training machinery
linked in.  Here the same surface is a small class over the Symbol frontend:
creation infers shapes once and compiles ONE XLA inference module
(jit-cached per shape signature), `reshape` re-specializes, and
`set_input/forward/get_output` mirror `MXPredSetInput/MXPredForward/
MXPredGetOutput`.  Partial-output predictors (`MXPredCreatePartialOut`)
select internal symbol outputs via ``get_internals()``.

Reference map:
- `MXPredCreate` / `MXPredCreatePartialOut` → ``Predictor(...)`` /
  ``Predictor(..., output_names=[...])`` (c_predict_api.cc)
- `MXPredReshape`       → ``Predictor.reshape``
- `MXPredSetInput`      → ``Predictor.set_input``
- `MXPredForward`       → ``Predictor.forward``
- `MXPredGetOutputShape`→ ``Predictor.get_output_shape``
- `MXPredGetOutput`     → ``Predictor.get_output``
- `MXNDListCreate`      → ``load_ndarray_file``
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from .executor import Executor
from .telemetry import tracing


def load_ndarray_file(fname):
    """Load a name→array file saved by ``nd.save`` (reference
    ``MXNDListCreate``, c_predict_api.cc; e.g. the mean-image file used by
    image-classification deployments)."""
    return nd.load(fname)


class Predictor:
    """Inference-only executor over a saved (symbol, params) pair.

    Parameters
    ----------
    symbol : Symbol or str
        A Symbol, a path to ``*-symbol.json``, or a JSON string.
    params : dict or str
        ``{name: NDArray}`` (``arg:``/``aux:`` prefixes optional, matching
        the checkpoint format) or a path to a ``*.params`` file.
    input_shapes : dict
        name → shape for every data input (reference ``input_keys`` +
        ``input_shape_data`` of MXPredCreate).
    output_names : list of str, optional
        Select internal outputs by name (``MXPredCreatePartialOut``); names
        may be given with or without the ``_output`` suffix.
    dtype : str
        Input/param compute dtype (deployments may pass "bfloat16" for
        TPU-native inference; params are cast on copy).
    """

    def __init__(self, symbol, params, input_shapes, ctx=None,
                 output_names=None, dtype="float32"):
        if isinstance(symbol, str):
            s = symbol.lstrip()
            symbol = (sym_mod.load_json(symbol) if s.startswith("{")
                      else sym_mod.load(symbol))
        if output_names:
            internals = symbol.get_internals()
            avail = internals.list_outputs()
            picked = []
            for name in output_names:
                cand = name if name in avail else name + "_output"
                if cand not in avail:
                    raise ValueError(
                        "output %r not in graph (have e.g. %s)"
                        % (name, avail[:8]))
                picked.append(internals[avail.index(cand)])
            symbol = sym_mod.Group(picked) if len(picked) > 1 else picked[0]
        arg_params, aux_params = self._load_params(params)
        self._init_bound(symbol, dtype, ctx, arg_params, aux_params,
                         input_shapes)

    def _init_bound(self, symbol, dtype, ctx, arg_params, aux_params,
                    input_shapes):
        """Shared init tail for ``__init__`` and ``with_shapes`` — one
        place that knows every field a bound Predictor carries, so clones
        can never silently miss a later-added attribute."""
        self._symbol = symbol
        self._dtype = dtype
        self._ctx = ctx
        self._arg_params = arg_params
        self._aux_params = aux_params
        self._input_names = list(input_shapes.keys())
        self._build(dict(input_shapes))

    @staticmethod
    def _load_params(params):
        if isinstance(params, str):
            params = nd.load(params)
        args, aux = {}, {}
        for k, v in params.items():
            if k.startswith("arg:"):
                args[k[4:]] = v
            elif k.startswith("aux:"):
                aux[k[4:]] = v
            else:
                args[k] = v
        return args, aux

    def _build(self, input_shapes):
        self._input_shapes = input_shapes
        # every arg (inputs AND weights) is allocated in the deploy dtype, so
        # dtype="bfloat16" really computes in bf16 on the MXU; aux states
        # (BN running stats) stay float32, the mixed-precision norm
        exe = self._symbol.simple_bind(
            ctx=self._ctx, grad_req="null",
            type_dict={n: self._dtype for n in self._symbol.list_arguments()},
            **input_shapes)
        # inputs (data/label) are fed per-call, never from the param file
        # (reference c_predict_api.cc keeps arg_params and input keys disjoint)
        weights = {k: v for k, v in self._arg_params.items()
                   if k not in input_shapes}
        exe.copy_params_from(weights, self._aux_params,
                             allow_extra_params=True)
        self._exec: Executor = exe
        self._outputs = None

    # -- c_predict_api surface ---------------------------------------------
    def set_input(self, name, value):
        """Stage one input array (``MXPredSetInput``)."""
        if name not in self._input_shapes:
            raise KeyError("unknown input %r (declared: %s)"
                           % (name, self._input_names))
        arr = np.asarray(
            value.asnumpy() if hasattr(value, "asnumpy") else value)
        if tuple(arr.shape) != tuple(self._input_shapes[name]):
            raise ValueError(
                "input %r shape %s != declared %s (use reshape())"
                % (name, arr.shape, self._input_shapes[name]))
        # NDArray assignment casts to the bound dtype (incl. bfloat16)
        self._exec.arg_dict[name][:] = arr

    def forward(self, **kwargs):
        """Run inference (``MXPredForward``); inputs may also be passed as
        kwargs, matching ``Executor.forward``."""
        for k, v in kwargs.items():
            self.set_input(k, v)
        # nests under the serving engine's execute span (or any other active
        # trace); NULL when no sampled trace is live on this thread
        with tracing.span("predictor_forward"):
            self._outputs = self._exec.forward(is_train=False)
        return self._outputs

    def get_output_shape(self, index=0):
        """Output shape without running (``MXPredGetOutputShape``)."""
        _, out_shapes, _ = self._symbol.infer_shape(**self._input_shapes)
        return tuple(out_shapes[index])

    def get_output(self, index=0):
        """Fetch an output as numpy (``MXPredGetOutput`` copies to host)."""
        if self._outputs is None:
            self.forward()
        return self._outputs[index].asnumpy()

    # -- AOT warmup (MXNET_AOT_CACHE, compile_cache.py, ISSUE 6) ------------
    def aot_lower(self):
        """Stage 1 of the ahead-of-time compile split: restore this
        predictor's inference executable from the persistent cache, or
        trace+lower it for compiling.  Host-only work — the serving warmup
        runs this for every ladder bucket concurrently.  None when
        ``MXNET_AOT_CACHE`` is off."""
        return self._exec.aot_lower(is_train=False)

    def aot_finalize(self, handle):
        """Stage 2: compile-or-install the executable behind ``forward`` so
        the first real request dispatches hot.  → finalize row with
        ``source`` ("cached"/"disk"/"compile"), ``lower_s``, ``compile_s``."""
        return self._exec.aot_finalize(handle, is_train=False)

    def aot_warm(self):
        """One-call AOT prepare (lower + compile-or-restore), for
        deployments that warm a bare Predictor without an Engine.  None when
        the cache is off."""
        handle = self.aot_lower()
        return None if handle is None else self.aot_finalize(handle)

    def pass_stats(self):
        """Graph-pass results for this predictor's lowered plans
        (``{"eval": {...nodes_pre/nodes_post/seconds...}}`` once the first
        forward — or AOT lower — has run; empty with ``MXNET_GRAPH_PASSES``
        off).  The serving warmup report surfaces these per bucket."""
        return self._exec.pass_stats()

    def check(self):
        """Graph-IR analyzer diagnostics (``mxnet_tpu.analysis``, ISSUE 8)
        for this predictor's eval plan -> sorted ``[Diagnostic]``.  Static
        (abstract shapes only, nothing compiles or runs); the serving
        warmup surfaces the per-bucket count when
        ``MXNET_GRAPH_ANALYZERS=1``."""
        return self._exec.check(is_train=False)

    def precision_plan(self):
        """The cast-plan artifact (``analysis.numerics.CastPlan``, ISSUE
        11) for this predictor's eval plan: per-node ``bf16_safe |
        fp32_accum | fp32_only`` verdicts + a fingerprint — what the
        deployment-tier bf16 pass (ROADMAP item 3) will consume to build
        this predictor's mixed-precision twin.  Serving warmup surfaces
        the verdict counts per bucket when ``MXNET_GRAPH_ANALYZERS=1``."""
        return self._exec.precision_plan(is_train=False)

    def with_shapes(self, input_shapes):
        """A sibling Predictor specialized to ``input_shapes``, sharing this
        one's symbol and loaded params — the cheap path for holding MANY
        shape specializations of one checkpoint alive at once (the serving
        engine's per-bucket predictors).  Unlike ``reshape`` this does not
        disturb ``self``; unlike re-calling ``Predictor(...)`` it re-parses
        nothing, and weight device buffers are shared wherever the deploy
        dtype matches the stored dtype (``NDArray._rebind`` keeps the same
        jax array), so N buckets cost ~1x the weights in HBM.  An explicit
        precision tier (``with_precision``) carries over, so every bucket
        of a twin serves the same tier."""
        clone = object.__new__(Predictor)
        clone._init_bound(self._symbol, self._dtype, self._ctx,
                          self._arg_params, self._aux_params, input_shapes)
        if clone._exec._precision_tier != self._exec._precision_tier \
                or self._exec._calibration is not None:
            clone._exec.set_precision_tier(self._exec._precision_tier,
                                           self._exec._calibration)
        return clone

    def with_precision(self, tier, calibration=None):
        """The precision-tier twin of this predictor (ISSUE 15): same
        symbol, same loaded params — weight device buffers shared exactly
        like ``with_shapes``, so one checkpoint serves fp32 and bf16/int8
        twins side by side for ~1x the weights in HBM — but the eval plan
        is rewritten by the ``tier`` pass list (``graph_passes/precision``):
        ``"bf16"`` = CastPlan-driven bf16 regions with fp32 accumulation,
        ``"int8"`` = calibration-based int8 conv/FC (pass the
        :func:`graph_passes.precision.calibrate` table — without one the
        int8 rewrite has no coverage and leaves every node alone);
        ``"fp32"``/None = a plain twin with any ambient
        ``MXNET_PRECISION_TIER`` explicitly cleared.

        The twin's outputs are held to the tier's declared tolerance
        contract vs this (fp32) predictor
        (``graph_passes.precision.tier_tolerance``); its AOT-cache keys
        carry the tier + calibration fingerprints, so twins never share
        executables with their fp32 sibling."""
        clone = object.__new__(Predictor)
        clone._init_bound(self._symbol, self._dtype, self._ctx,
                          self._arg_params, self._aux_params,
                          dict(self._input_shapes))
        clone._exec.set_precision_tier(tier, calibration)
        return clone

    @property
    def precision_tier(self):
        """This predictor's tier label — ``"fp32"``, ``"bf16"``, or
        ``"int8"`` (the warmup-row / SERVE_BENCH discriminator)."""
        return self._exec.precision_tier

    @property
    def int8_sites(self):
        """The int8 rewrite's drift-baseline export for this predictor's
        lowered eval plan — ``{site -> {input, lo, hi, a_scale}}`` where
        ``input`` is the STRUCTURAL env name the site's calibrated range
        was keyed under (telemetry/qualityplane.py compares live ranges
        against this).  Empty until the plan lowers (first forward / AOT
        lower), and for any non-int8 tier.  Re-stashed from the new
        table when a twin is rebuilt via ``with_precision``, so the
        quality plane's drift baseline always follows the executable
        actually serving."""
        return dict(self._exec._int8_sites)

    def reshape(self, input_shapes):
        """Re-specialize to new input shapes (``MXPredReshape``) — a new jit
        signature; weight buffers are reused in place (``Executor.reshape``
        keeps same-shaped arrays; shape-changing weights is an error, same
        as the reference's shape check).  An explicit precision tier
        (``with_precision``) carries across the re-bind, exactly like
        ``with_shapes`` — a reshaped twin keeps serving its tier."""
        shapes = dict(self._input_shapes)
        shapes.update(input_shapes)
        self._input_shapes = shapes
        old = self._exec
        self._exec = old.reshape(**shapes)
        if self._exec._precision_tier != old._precision_tier \
                or old._calibration is not None:
            self._exec.set_precision_tier(old._precision_tier,
                                          old._calibration)
        want = (self._dtype if self._dtype == "bfloat16"
                else str(np.dtype(self._dtype)))
        for n in self._input_names:
            arr = self._exec.arg_dict[n]
            if str(arr.dtype) != want:
                self._exec.arg_dict[n] = nd.zeros(arr.shape, dtype=self._dtype)
        self._outputs = None

    @property
    def outputs(self):
        return self._outputs


def create(symbol_file, param_file, input_shapes, ctx=None, output_names=None,
           dtype="float32"):
    """Functional spelling of ``MXPredCreate(PartialOut)``."""
    return Predictor(symbol_file, param_file, input_shapes, ctx=ctx,
                     output_names=output_names, dtype=dtype)
