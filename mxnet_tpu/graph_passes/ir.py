"""Graph IR for the symbolic pass layer (ISSUE 7).

The unit of optimization is the *execution plan* the Executor already
evaluates: a topologically ordered list of ``(node, in_names)`` entries plus
the ordered head names — exactly what ``Executor._make_plan`` produces and
``Executor._graph_fn`` walks.  :class:`Graph` wraps that plan as an immutable
value (tuples all the way down) so every pass is a pure function
``Graph -> Graph`` and the unoptimized plan can never be mutated in place —
``MXNET_GRAPH_PASSES=0`` must stay byte-identical to a build without this
package.

Three node flavors appear in a plan:

* captured ``Symbol`` nodes (the common case — everything ``capture`` emits);
* :class:`PlanNode` — a pass-synthesized replacement node (e.g. the
  inference BN-into-affine rewrite) carrying a :class:`SynthOp`.  Both
  duck-type the exact attribute surface ``Executor._graph_fn`` reads
  (``op.fn``, ``op.attr_names``, ``op.aux``, ``op.aux_update``, ``attrs``,
  ``name``, ``num_outputs``), so the executor needs no case split;
* *baked constants* — nodes folded away entirely, their output values moved
  into ``Graph.constants`` (seeded into the evaluation env before any node
  runs).
"""
from __future__ import annotations

__all__ = ["Graph", "PlanNode", "SynthOp", "capture", "node_out_names",
           "node_call_attrs", "node_attr", "REDUCE", "EXP_RANGE",
           "CANCELLATION", "NEUTRAL", "SENSITIVITY_VERSION",
           "op_sensitivity"]


def node_attr(node, key, default=None):
    """A plan node's attr with the op's default filled in — the ONE
    attrs-with-defaults resolution, shared by :func:`op_sensitivity`, the
    graph analyzers, and the numerics interval transfer functions."""
    defaults = getattr(node.op, "defaults", {}) or {}
    return node.attrs.get(key, defaults.get(key, default))


def node_call_attrs(node, key, is_train):
    """The attr dict a plan node's ``op.fn`` is called with — the ONE
    implementation of the per-node PRNG-stream fold and ``training``
    fill-in, shared by ``Executor._graph_fn`` (real evaluation) and
    ``analysis._abstract_walk`` (``jax.eval_shape``), so the abstract walk
    can never drift from what actually lowers."""
    import zlib

    import jax

    attrs = dict(node.attrs)
    if "key" in node.op.attr_names and "key" not in attrs:
        # stable per-node stream: crc32 is process-independent
        # (PYTHONHASHSEED-proof), keeping seeded runs reproducible
        attrs["key"] = jax.random.fold_in(key, zlib.crc32(node.name.encode()))
    if "training" in node.op.attr_names and "training" not in attrs:
        attrs["training"] = is_train
    return attrs


# -- numeric-sensitivity registry (ISSUE 11) ---------------------------------
#
# Colocated with ``node_call_attrs`` ON PURPOSE: both describe how a plan
# node actually evaluates, and the numerics analyzer
# (``analysis/numerics.py``) consults this table while walking plans the
# exact way ``Executor._graph_fn`` does — keeping the table next to the one
# evaluation contract means a new op (or a pass-synthesized SynthOp) gets
# its sensitivity class reviewed in the same file that defines how it runs,
# so the two can't drift apart in separate modules.
#
# Classes (the cast-plan verdict ladder builds on these):
#
#   REDUCE        accumulation over many elements (sum/mean/dot/conv/
#                 BN-stats): bf16 inputs are fine, but the ACCUMULATOR must
#                 stay fp32 — bf16's 8 mantissa bits lose one part in 256
#                 per add, and a 10^4-element reduction drifts visibly.
#   EXP_RANGE     exp/log-family range hazard: exp overflows/saturates
#                 outside a narrow input band and log amplifies error near
#                 0 — safe in low precision ONLY when interval analysis
#                 bounds the input.
#   CANCELLATION  subtraction of near-equal quantities (variance chains,
#                 normalization stats): catastrophic cancellation — keep
#                 fp32 regardless of input bounds.
#   NEUTRAL       element-local, monotone, or data-movement ops: safe to
#                 drop to bf16 whenever their inputs are.
#
# Bump SENSITIVITY_VERSION on ANY table/classification change: it enters
# every cast-plan fingerprint and the AOT-cache environment fingerprint
# (compile_cache._env_fingerprint), so executables compiled under an older
# classification miss cleanly instead of restoring stale numerics.

REDUCE = "reduce"
EXP_RANGE = "exp_range"
CANCELLATION = "cancellation"
NEUTRAL = "neutral"

SENSITIVITY_VERSION = 1

_OP_SENSITIVITY = {
    # accumulating reductions + matmul/conv contractions
    "sum": REDUCE, "mean": REDUCE, "prod": REDUCE, "nansum": REDUCE,
    "nanprod": REDUCE, "add_n": REDUCE, "norm": REDUCE,
    "_square_sum": REDUCE, "dot": REDUCE, "batch_dot": REDUCE,
    "FullyConnected": REDUCE, "Convolution": REDUCE, "Deconvolution": REDUCE,
    "Correlation": REDUCE, "L2Normalization": REDUCE,
    "softmax_cross_entropy": REDUCE, "_linalg_gemm": REDUCE,
    "_linalg_gemm2": REDUCE, "_linalg_syrk": REDUCE,
    "_linalg_sumlogdiag": REDUCE, "khatri_rao": REDUCE,
    # exp/log-family range hazards
    "exp": EXP_RANGE, "expm1": EXP_RANGE, "log": EXP_RANGE,
    "log1p": EXP_RANGE, "log2": EXP_RANGE, "log10": EXP_RANGE,
    "softmax": EXP_RANGE, "log_softmax": EXP_RANGE, "softmin": EXP_RANGE,
    "SoftmaxActivation": EXP_RANGE, "SoftmaxOutput": EXP_RANGE,
    "gamma": EXP_RANGE, "gammaln": EXP_RANGE, "sinh": EXP_RANGE,
    "cosh": EXP_RANGE, "_power": EXP_RANGE, "broadcast_power": EXP_RANGE,
    "_rpower_scalar": EXP_RANGE,
    # catastrophic-cancellation chains (normalization statistics)
    "moments": CANCELLATION, "BatchNorm": CANCELLATION,
    "LayerNorm": CANCELLATION, "InstanceNorm": CANCELLATION,
    "LRN": CANCELLATION,
}


def op_sensitivity(node):
    """Sensitivity class of a plan node (captured Symbol node or
    pass-synthesized :class:`PlanNode`), resolving the attr-dependent
    cases: avg/global Pooling accumulates (max/min pooling only compares),
    Activation dispatches on ``act_type``.  Unknown ops default NEUTRAL —
    the cast-plan consumer treats only the listed classes specially, and a
    wrong NEUTRAL shows up as a diagnostics gap, not a crash."""
    opname = getattr(node.op, "name", "")
    if opname == "Pooling":
        pool = node_attr(node, "pool_type", "max")
        return REDUCE if pool in ("avg", "sum", "lp") else NEUTRAL
    if opname == "Activation":
        act = node.attrs.get("act_type")
        if act in ("softrelu",):  # log(1+exp(x))
            return EXP_RANGE
        return NEUTRAL
    return _OP_SENSITIVITY.get(opname, NEUTRAL)


class SynthOp:
    """Duck-typed OpDef stand-in for pass-synthesized nodes.

    Carries only what ``Executor._graph_fn`` touches; ``aux_update`` is
    always None (synthesized nodes never own aux state), so the executor's
    aux branch — the one place ``node.inputs`` / ``_node_input_names`` are
    consulted — can never fire on one.
    """

    __slots__ = ("name", "fn", "attr_names")

    # class-level so every instance agrees with OpDef's surface
    aux = ()
    aux_update = None
    mutates = ()
    inputs_fn = None
    variadic = False
    arg_names = ()
    defaults = {}

    def __init__(self, name, fn, attr_names=()):
        self.name = name
        self.fn = fn
        self.attr_names = tuple(attr_names)

    def __repr__(self):
        return "SynthOp(%s)" % self.name


class PlanNode:
    """A pass-synthesized plan node (same attribute surface as a captured
    Symbol node, minus the graph-structure methods no pass output needs)."""

    __slots__ = ("op", "attrs", "name", "num_outputs", "inputs")

    is_var = False

    def __init__(self, op, attrs, name, num_outputs=1):
        self.op = op
        self.attrs = dict(attrs)
        self.name = name
        self.num_outputs = num_outputs
        self.inputs = []

    def __repr__(self):
        return "PlanNode(%s:%s)" % (self.op.name, self.name)


def node_out_names(node):
    """The env names a plan node's outputs bind to — must mirror
    ``Executor._graph_fn``'s naming exactly."""
    if node.num_outputs > 1:
        return ["%s_output%d" % (node.name, i)
                for i in range(node.num_outputs)]
    return ["%s_output" % node.name]


def capture(symbol):
    """Capture a Symbol DAG as ``(plan, head_names)`` — the shared front end
    of ``Executor._make_plan`` and the standalone :func:`node_counts`
    surface (``Symbol.debug_str`` / ``visualization.print_summary``).

    ``plan`` is ``[(node, [input_env_name, ...]), ...]`` in topological
    order (vars excluded — their values enter the env from the bound
    arg/aux arrays); ``head_names`` lists the env name of every output in
    ``Symbol.list_outputs()`` order.
    """
    from ..symbol.symbol import _sym_out_name

    plan = []
    for node in symbol._walk():
        if node.is_var:
            continue
        plan.append((node, [_sym_out_name(i) for i in node.inputs]))
    head_names = []
    for node, idx in symbol._outputs_of():
        base = node._base() if node.out_index is not None else node
        head_names.append(_sym_out_name(node) if node.is_var else (
            "%s_output%d" % (base.name, idx) if base.num_outputs > 1
            else "%s_output" % base.name))
    return plan, head_names


class Graph:
    """Immutable pass-layer value: ``entries`` (topo-ordered
    ``(node, in_names)`` pairs), ``heads`` (ordered output env names), and
    ``constants`` (env name -> baked value, seeded before evaluation)."""

    __slots__ = ("entries", "heads", "constants")

    def __init__(self, entries, heads, constants=None):
        self.entries = tuple((node, tuple(in_names))
                             for node, in_names in entries)
        self.heads = tuple(heads)
        self.constants = dict(constants) if constants else {}

    @property
    def n_nodes(self):
        return len(self.entries)

    def __repr__(self):
        return "Graph(%d nodes, %d heads, %d constants)" % (
            len(self.entries), len(self.heads), len(self.constants))
