"""The standard pass pipeline (ISSUE 7) — four passes, registered in the
order they run:

1. ``constant_fold``    — nodes whose transitive inputs are all
   attr-constants (zero-tensor-input ops like ``_zeros``/``_arange`` seed
   the lattice) evaluate ONCE at plan time through the op registry and
   become baked constants; XLA then sees a literal instead of re-tracing
   the producing subgraph every bucket/signature.
2. ``common_subexpr_merge`` — structural hash on (op identity, canonical
   attrs, resolved input names, output arity); later duplicates redirect
   their consumers (and heads) onto the first occurrence.  Stochastic nodes
   are NEVER merged: each folds a distinct PRNG stream keyed by its node
   name, and deduping them would silently correlate draws.  The duplicate
   chain itself is left in place for the DCE sweep — redirect-then-sweep
   keeps this pass a pure rename.
3. ``inference_rewrite`` — ``is_train=False`` plans only: Dropout (identity
   in eval mode) is deleted outright, and BatchNorm with frozen moving
   stats is replaced by a synthesized scale+shift affine node computing the
   *same expression sequence* as the eval BN branch (bit-identical outputs,
   none of the train-path machinery traced).
4. ``dead_node_elim``    — reachability from heads (``get_internals``-style,
   walked in reverse topological order); train-mode plans additionally root
   every aux-updating node, since its moving-stat fold is a real side
   effect even when no head consumes its outputs.  This is the sweep that
   collects the branches the redirect passes orphaned.

Every pass is a pure ``Graph -> Graph`` function over the immutable IR
(``ir.Graph``); correctness-critical exclusions are centralized in the
``_fold_ok`` / ``_cse_ok`` predicates below.
"""
from __future__ import annotations

import numpy as np

from . import register_pass
from .ir import Graph, PlanNode, SynthOp, node_out_names

# never bake a constant bigger than this — folding exists to shrink traced
# graphs, not to bloat serialized executables with giant literals
_FOLD_MAX_BYTES = 64 << 20

# op families the passes must not touch: arbitrary user Python (may be
# impure), and native-backed ops
_OPAQUE_OPS = ("Custom",)


def _opaque(op):
    return op.name in _OPAQUE_OPS or op.name.startswith("_native")


def _fold_ok(node):
    """A node may be folded iff its value is a pure function of its attrs
    and inputs in BOTH modes: no PRNG stream (``key``), no train/eval
    branch (``training``), no aux state, no in-place mutation contract."""
    op = node.op
    if "key" in op.attr_names or "training" in op.attr_names:
        return False
    if op.aux or op.aux_update is not None or op.mutates:
        return False
    return not _opaque(op)


def _cse_ok(node, is_train):
    """A node may be merged with a structural twin iff the two are
    observationally identical: stochastic ops fold distinct per-name PRNG
    keys (never equal), and in train mode an aux-updating node's moving-stat
    fold must run once per NODE, not once per equivalence class."""
    op = node.op
    if "key" in op.attr_names or op.mutates:
        return False
    if is_train and (op.aux or op.aux_update is not None):
        return False
    return not _opaque(op)


def _canon(v):
    if isinstance(v, np.ndarray):
        return _canon(v.tolist())
    if isinstance(v, (list, tuple)):
        return tuple(_canon(e) for e in v)
    return v


def _attr_sig(attrs):
    """Canonical, order-independent attr signature (raises for attr values
    without a stable repr — the caller skips such nodes)."""
    return repr(sorted((k, repr(_canon(v))) for k, v in attrs.items()))


def _eval_outs(node, args):
    """Evaluate one node through the registry exactly as
    ``Executor._graph_fn`` would (including the hidden-output trim)."""
    res = node.op.fn(*args, **dict(node.attrs))
    outs = res if isinstance(res, tuple) else (res,)
    if len(outs) > 1 and node.num_outputs == 1:
        outs = outs[:1]
    return outs


@register_pass("constant_fold", version=1)
def constant_fold(graph, is_train):
    const = dict(graph.constants)
    kept = []
    for node, in_names in graph.entries:
        if not (_fold_ok(node) and all(n in const for n in in_names)):
            kept.append((node, in_names))
            continue
        try:
            outs = _eval_outs(node, [const[n] for n in in_names])
            nbytes = sum(int(getattr(o, "nbytes", _FOLD_MAX_BYTES + 1))
                         for o in outs)
        except Exception:
            kept.append((node, in_names))
            continue
        if nbytes > _FOLD_MAX_BYTES or len(outs) < node.num_outputs:
            kept.append((node, in_names))
            continue
        for nm, v in zip(node_out_names(node), outs):
            const[nm] = v
    if len(kept) == len(graph.entries):
        return graph
    return Graph(kept, graph.heads, const)


@register_pass("common_subexpr_merge", version=1)
def common_subexpr_merge(graph, is_train):
    rename = {}
    seen = {}
    entries = []
    for node, in_names in graph.entries:
        in_names = tuple(rename.get(n, n) for n in in_names)
        entries.append((node, in_names))
        if not _cse_ok(node, is_train):
            continue
        try:
            sig = (id(node.op), node.num_outputs, in_names,
                   _attr_sig(node.attrs))
        except Exception:
            continue
        rep = seen.get(sig)
        if rep is None:
            seen[sig] = node
        else:  # later twin: consumers re-point at the representative
            for mine, theirs in zip(node_out_names(node),
                                    node_out_names(rep)):
                rename[mine] = theirs
    if not rename:
        return graph
    return Graph(entries, (rename.get(h, h) for h in graph.heads),
                 graph.constants)


def _bn_affine_fn(data, gamma, beta, moving_mean, moving_var, *,
                  eps, fix_gamma, axis):
    """Frozen-stats BatchNorm as a per-channel affine — the eval branch of
    ``ops.nn.batch_norm`` verbatim (same expression sequence, so outputs
    are bit-identical), with the train branch and hidden (mean, var)
    outputs never entering the trace."""
    import jax.numpy as jnp

    ax = axis % data.ndim
    bshape = tuple(data.shape[ax] if i == ax else 1
                   for i in range(data.ndim))
    mean, var = moving_mean, moving_var
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    scale = (g / jnp.sqrt(var + eps)).astype(data.dtype).reshape(bshape)
    shift = (beta - mean * g / jnp.sqrt(var + eps)).astype(
        data.dtype).reshape(bshape)
    return data * scale + shift


_BN_AFFINE_OP = SynthOp("_bn_affine", _bn_affine_fn,
                        attr_names=("eps", "fix_gamma", "axis"))


def _attr_of(node, key):
    return node.attrs.get(key, node.op.defaults.get(key))


@register_pass("inference_rewrite", version=1)
def inference_rewrite(graph, is_train):
    if is_train:
        return graph
    rename = {}
    entries = []
    changed = False
    for node, in_names in graph.entries:
        in_names = tuple(rename.get(n, n) for n in in_names)
        opname = getattr(node.op, "name", "")
        explicit_train = bool(node.attrs.get("training"))
        if (opname == "Dropout" and node.num_outputs == 1 and in_names
                and not explicit_train
                and _attr_of(node, "mode") != "always"):
            # eval-mode dropout is the identity: delete the node, re-point
            # its consumers (and any head) straight at its data input
            rename["%s_output" % node.name] = in_names[0]
            changed = True
            continue
        if (opname == "BatchNorm" and node.num_outputs == 1
                and len(in_names) == 5 and not explicit_train
                and not node.attrs.get("output_mean_var")):
            new = PlanNode(
                _BN_AFFINE_OP,
                {"eps": _attr_of(node, "eps"),
                 "fix_gamma": _attr_of(node, "fix_gamma"),
                 "axis": _attr_of(node, "axis")},
                node.name)  # same name -> same output env name, heads keep
            entries.append((new, in_names))
            changed = True
            continue
        entries.append((node, in_names))
    if not changed:
        return graph
    return Graph(entries, (rename.get(h, h) for h in graph.heads),
                 graph.constants)


@register_pass("dead_node_elim", version=1)
def dead_node_elim(graph, is_train):
    entries = list(graph.entries)
    needed = set(graph.heads)
    keep = [False] * len(entries)
    for i in range(len(entries) - 1, -1, -1):
        node, in_names = entries[i]
        live = any(nm in needed for nm in node_out_names(node))
        if is_train and node.op.aux_update is not None and node.op.aux:
            live = True  # moving-stat fold is a side effect heads can't see
        if live:
            keep[i] = True
            needed.update(in_names)
    if all(keep):
        return graph
    kept = [e for e, k in zip(entries, keep) if k]
    used = set(graph.heads)
    for _, in_names in kept:
        used.update(in_names)
    return Graph(kept, graph.heads,
                 {k: v for k, v in graph.constants.items() if k in used})
