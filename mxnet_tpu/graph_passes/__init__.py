"""Graph-pass layer over the captured Symbol DAG (ISSUE 7) — the Relay move.

The reference framework ran NNVM passes (Gradient / InferShape / PlanMemory)
over its graph IR before execution; our Symbol -> Executor path used to lower
pass-free, so XLA traced dead branches, re-traced duplicated subgraphs per
bucket, and kept inference-time BatchNorms as full normalization ops.  This
package optimizes the high-level IR *first* (PAPERS.md 1810.00952 /
1904.08368): ``Executor`` runs the registered pipeline over its execution
plan before jax ever sees the graph, so both the Predictor bucket ladder and
the fused train step trace and compile smaller XLA modules — which also
directly shrinks the cold-compile cost the AOT cache (ISSUE 6) amortizes.

Surface:

* :func:`enabled` — the ``MXNET_GRAPH_PASSES`` gate (default ON; ``0``
  makes every consumer byte-identical to a build without this package).
  The Executor snapshots the gate at bind time, so one executor never mixes
  optimized and raw plans.
* :func:`register_pass` — decorator adding a pure ``Graph -> Graph``
  function to the pipeline; registration order IS execution order, and the
  (name, version) list is the pipeline fingerprint.
* :func:`optimize` — run the pipeline over a captured plan; returns the
  optimized :class:`~.ir.Graph` plus per-pass node-count/time stats.
* :func:`pipeline_fingerprint` — stable string identity of the configured
  pipeline, or None when the gate is off.  ``compile_cache.CachedFunction``
  folds it into every logical cache key and the verified environment
  fingerprint, so toggling passes (or shipping a changed pass version) is a
  clean AOT-cache miss, never a stale restore.
* :func:`node_counts` — standalone (symbol -> (pre, post)) counting for
  printed summaries (``Symbol.debug_str``, ``visualization.print_summary``).
"""
from __future__ import annotations

import time

from ..base import env_flag

__all__ = ["enabled", "register_pass", "pipeline", "pipeline_fingerprint",
           "optimize", "node_counts", "Graph", "PlanNode", "SynthOp",
           "capture"]

_PASSES = []  # [(name, version, fn)] — registration order is run order


def enabled():
    """``MXNET_GRAPH_PASSES`` gate (docs/ENV_VARS.md) — default ON."""
    return env_flag("MXNET_GRAPH_PASSES", default="1")


def register_pass(name, version=1):
    """Register a pure ``fn(graph, is_train) -> graph`` pipeline pass.
    Bump ``version`` on any behavior change: it enters the pipeline
    fingerprint, invalidating persisted executables built by the old
    pipeline."""
    def _reg(fn):
        _PASSES.append((str(name), int(version), fn))
        return fn
    return _reg


def pipeline():
    """The registered (name, version) pipeline, in run order."""
    return tuple((n, v) for n, v, _ in _PASSES)


def pipeline_fingerprint():
    """Stable identity of the active pipeline for cache keys, or None when
    the gate is off (so disabled builds produce pre-pass-era keys,
    byte-identical).  With ``MXNET_PRECISION_TIER`` set (ISSUE 15) the
    active tier's pass fingerprint — pass names:versions plus the numerics
    contract versions — is appended, so tier twins can never share an
    AOT-cache entry (or an env fingerprint) with fp32 plans; unset keeps
    the string byte-identical to pre-tier builds."""
    if not enabled():
        return None
    fp = "|".join("%s:%d" % (n, v) for n, v, _ in _PASSES)
    from . import precision as _precision

    tier_fp = _precision.tier_fingerprint()
    return fp if tier_fp is None else "%s|%s" % (fp, tier_fp)


def optimize(plan, head_names, is_train):
    """Run the pipeline over a captured plan.

    -> ``(graph, stats)`` where ``stats`` is::

        {"mode": "train"|"eval", "nodes_pre": int, "nodes_post": int,
         "seconds": float,
         "passes": [{"pass", "version", "nodes_in", "nodes_out",
                     "seconds"}, ...]}
    """
    g = Graph(plan, head_names)
    pre = g.n_nodes
    rows = []
    t_all = time.perf_counter()
    for name, version, fn in _PASSES:
        t0 = time.perf_counter()
        n_in = g.n_nodes
        g = fn(g, bool(is_train))
        rows.append({"pass": name, "version": version, "nodes_in": n_in,
                     "nodes_out": g.n_nodes,
                     "seconds": round(time.perf_counter() - t0, 6)})
    stats = {"mode": "train" if is_train else "eval",
             "nodes_pre": pre, "nodes_post": g.n_nodes,
             "seconds": round(time.perf_counter() - t_all, 6),
             "passes": rows}
    return g, stats


def node_counts(symbol, is_train=False):
    """(captured, post-pass) plan node counts for ``symbol`` in the given
    mode, or None when the gate is off — the cheap introspection surface
    behind ``Symbol.debug_str`` and ``visualization.print_summary``."""
    if not enabled():
        return None
    plan, heads = capture(symbol)
    try:
        g, _ = optimize(plan, heads, is_train)
    except Exception:
        return None  # a summary printer must never fail on an odd graph
    return len(plan), g.n_nodes


from .ir import Graph, PlanNode, SynthOp, capture  # noqa: E402
from . import passes  # noqa: E402,F401  (registers the standard pipeline)
from . import precision  # noqa: E402,F401  (the ISSUE 15 deploy tier —
#   separate pass list gated on MXNET_PRECISION_TIER, run by the Executor
#   AFTER this pipeline on eval plans only; never enters _PASSES)
