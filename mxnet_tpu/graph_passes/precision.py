"""Precision-tier compilation passes (ISSUE 15) — the CastPlan consumer.

PR 11 shipped the *decision procedure* (``analysis/numerics.py``: per-node
``bf16_safe | fp32_accum | fp32_only`` verdicts behind the fingerprinted
``CastPlan`` contract) and PR 13 the *ruler* (the costplane
bytes_accessed/peak ledger).  This module is the rewrite tier that finally
spends the verdicts: deployment-only graph passes, in the Relay/TVM
"trade precision for bandwidth where analysis proves it safe" spirit
(PAPERS.md 1810.00952, 1802.04799), gated on ``MXNET_PRECISION_TIER``:

``bf16`` tier — ``fold_conv_affine`` then ``bf16_cast``:

* **fold_conv_affine** — a frozen-stats ``_bn_affine`` (ISSUE 7's eval
  BatchNorm rewrite) whose only consumer is fed by a Convolution /
  FullyConnected folds into that producer's weights at plan time: the
  scale/shift computed from the bound gamma/beta/moving stats bakes into a
  new constant weight (and bias), and the affine node disappears from the
  plan entirely — the const-fold machinery (``Graph.constants``) carries
  the folded tensors.
* **bf16_cast** — consumes the executor's structural-plan CastPlan
  (``Predictor.precision_plan()`` / ``Executor.precision_plan(is_train=
  False)``): ``bf16_safe`` regions run in bf16 (inputs cast at region
  entry, at most ONE cast node per (value, direction) — adjacent safe
  regions share casts, so the pass never adds more converts than region
  edges); ``fp32_accum`` contractions (Convolution/FullyConnected) take
  bf16 operands but accumulate fp32 via ``preferred_element_type``
  (``accum_dtype`` attr, ops/nn.py) and re-narrow their output;
  ``fp32_accum`` reductions run inside an fp32-island wrapper (operands
  upcast in-op, reduce in fp32, output re-narrowed); ``fp32_only`` nodes
  are untouched and always see fp32 operands.  Plan heads are cast back to
  their fp32-plan dtypes, so the pass-drift contract (shape_dtype
  analyzer) holds and twins stay drop-in.

``int8`` tier — ``fold_conv_affine`` then ``int8_rewrite``:

* **int8_rewrite** — calibration-based: :func:`calibrate` replays real
  batches through the structural eval plan recording per-tensor min/max
  (the runtime refinement of the numerics interval analysis — observed
  ranges where the static transfer functions said UNKNOWN); eligible
  Convolution/FullyConnected nodes (calibrated data input, baked-able
  weight, verdict not ``fp32_only``) rewrite to symmetric int8: per-channel
  weight scales + per-tensor activation scale baked as constants, integer
  conv/dot with int32 accumulation, fp32 dequant at the region exit.
  Uncalibrated or ``fp32_only`` nodes are left alone — the pass quantizes
  only what the table covers.

Contracts:

* **off path** — ``MXNET_PRECISION_TIER`` unset ⇒ this module rewrites
  nothing, ``pipeline_fingerprint()`` and every AOT-cache key stay
  byte-identical to a build without it (PR 7-style, tested).
* **fingerprint** — :func:`tier_fingerprint` = the tier's ``name:version``
  pass list + ``numerics.contract_fingerprint()``; it joins
  ``pipeline_fingerprint()`` (env-gated path) and the executor's
  AOT logical key (both paths), so a tier flip, a pass version bump, or a
  ``SENSITIVITY_VERSION``/``NUMERICS_VERSION`` bump each miss cleanly.
* **tolerance** — every pass declares rtol/atol vs the fp32 plan
  (:data:`TOLERANCE`); tests and ``ci/check_precision_tier.py`` hold twins
  to :func:`tier_tolerance` on fixed inputs, and the bf16 twin must show
  strictly lower ledger ``bytes_accessed`` than its fp32 sibling.
* **weights bake at first lowering** — ``fold_conv_affine`` and
  ``int8_rewrite`` read the executor's *bound* param values when the plan
  first lowers; mutating weights afterwards (``copy_params_from`` on a
  live twin) leaves stale baked constants — rebuild the twin
  (``Predictor.with_precision``) after a weight swap.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
import warnings

import numpy as np

from .ir import Graph, PlanNode, SynthOp, node_attr, node_out_names

__all__ = ["tier", "tier_name", "tier_fingerprint", "tier_passes",
           "tier_tolerance", "TOLERANCE", "apply", "TierContext",
           "calibrate", "CalibrationTable"]

VALID_TIERS = ("bf16", "int8")

# per-pass numeric-tolerance contracts vs the fp32 plan — THE acceptance
# surface: a pass whose rewrite cannot meet its row here must not ship a
# version bump, it must ship a fix.  Checked in tests/test_precision_tier.py
# and ci/check_precision_tier.py on the deploy-twin checkpoint.
TOLERANCE = {
    # algebraically exact modulo float reassociation (conv(x, W*s) vs
    # conv(x, W)*s): a few ulps through a conv chain
    "fold_conv_affine": {"rtol": 1e-4, "atol": 1e-5},
    # bf16 keeps 8 mantissa bits; fp32 accumulation bounds the drift to
    # per-op rounding, which compounds through the trunk
    "bf16_cast": {"rtol": 5e-2, "atol": 5e-2},
    # 8-bit symmetric quantization of weights AND activations: ~1/127
    # per tensor, compounded per rewritten contraction
    "int8_rewrite": {"rtol": 0.25, "atol": 0.1},
}

_WARNED = set()


def _warn_once(key, msg):
    if key not in _WARNED:
        _WARNED.add(key)
        warnings.warn(msg, stacklevel=3)


def tier():
    """The configured precision tier: ``"bf16"`` / ``"int8"``, or None when
    ``MXNET_PRECISION_TIER`` is unset/``0`` (docs/ENV_VARS.md).  An unknown
    value warns once and reads as off — a typo must not silently serve a
    differently-compiled fleet (the ops_server malformed-port stance)."""
    v = os.environ.get("MXNET_PRECISION_TIER", "").strip()
    if not v or v == "0":
        return None
    if v not in VALID_TIERS:
        _warn_once(("tier", v),
                   "MXNET_PRECISION_TIER=%r is not one of %s — precision "
                   "tier disabled" % (v, list(VALID_TIERS)))
        return None
    return v


def tier_name(t=None):
    """Human/report label for a tier value: the tier, or ``"fp32"`` when
    off — the warmup-row / SERVE_BENCH ``tier`` discriminator."""
    return (t if t is not None else tier()) or "fp32"


def tier_passes(t):
    """The registered ``(name, version, fn)`` pass list for ``t``, in run
    order.  Mirrors the standard pipeline's registration-order-is-run-order
    contract; bump a version on ANY behavior change (it enters
    :func:`tier_fingerprint` and hence every AOT-cache key)."""
    return _TIER_PASSES[t]


def tier_fingerprint(t=None):
    """Stable identity of the active tier for cache keys — the tier's
    ``name:version`` pass list joined with the numerics contract versions
    (``SENSITIVITY_VERSION``/``NUMERICS_VERSION``), or None when off.  A
    registry reclassification moves this fingerprint, the AOT key, and
    ``numerics.contract_fingerprint()`` together (tested), so an executable
    compiled from an old CastPlan can never be restored."""
    t = t if t is not None else tier()
    if not t:
        return None
    from ..analysis import numerics

    return "tier=%s|%s|%s" % (
        t, "|".join("%s:%d" % (n, v) for n, v, _ in _TIER_PASSES[t]),
        numerics.contract_fingerprint())


def tier_tolerance(t):
    """Composed rtol/atol contract for a whole tier (the loosest row among
    its passes) — what a twin's outputs are held to vs the fp32 plan."""
    rows = [TOLERANCE[n] for n, _, _ in _TIER_PASSES[t]]
    return {"rtol": max(r["rtol"] for r in rows),
            "atol": max(r["atol"] for r in rows)}


class TierContext:
    """Everything a tier pass may consult (built by ``Executor._opt_plan``
    at first eval lowering):

    ``cast_plan``    the structural-plan :class:`~..analysis.numerics.
                     CastPlan` (``Executor.precision_plan(is_train=False)``)
                     — the verdicts the bf16/int8 rewrites consume;
    ``arg_names`` / ``aux_names`` / ``arg_avals`` / ``aux_avals``
                     bound-input order + ShapeDtypeStructs (the abstract
                     walk the dtype map comes from — same fields as the
                     analysis ``GraphContext``, so ``_abstract_walk``
                     accepts this context directly);
    ``arg_values`` / ``aux_values``
                     name -> bound array (device ok) for plan-time weight
                     folding/quantization;
    ``calibration``  optional :class:`CalibrationTable` for the int8 tier.
    """

    is_train = False  # tier passes exist for eval plans only

    def __init__(self, cast_plan, arg_names, aux_names, arg_avals,
                 aux_avals, arg_values, aux_values, calibration=None):
        self.cast_plan = cast_plan
        self.arg_names = list(arg_names)
        self.aux_names = list(aux_names)
        self.arg_avals = arg_avals
        self.aux_avals = aux_avals
        self.arg_values = dict(arg_values)
        self.aux_values = dict(aux_values)
        self.calibration = calibration
        # fold_conv_affine renames a folded affine's output onto its
        # producer's env name; calibration recorded ranges on the
        # STRUCTURAL plan, so later passes must look the renamed value up
        # under its original (affine-output) name: {new name -> old name}
        self.calib_alias = {}
        # int8_rewrite exports every quantized site here — {site name ->
        # {input (STRUCTURAL env name, alias-resolved), lo, hi, a_scale}}
        # — the drift baseline the quality plane compares live activation
        # ranges against (telemetry/qualityplane.py).  Populated during
        # apply(), stashed by the executor alongside _tier_stats.
        self.int8_sites = {}

    def calib_range(self, name):
        """Calibrated (lo, hi) for an env name, resolved through any
        fold-pass rename — None without a table or coverage."""
        if self.calibration is None:
            return None
        return self.calibration.range(self.calib_alias.get(name, name))

    def value_of(self, graph, name):
        """Concrete host value for an env name (baked constant or bound
        arg/aux), or None when the name is runtime-only."""
        if name in graph.constants:
            return np.asarray(graph.constants[name])
        v = self.arg_values.get(name, self.aux_values.get(name))
        return None if v is None else np.asarray(v)


def apply(graph, t, ctx):
    """Run tier ``t``'s pass list over ``graph`` -> ``(graph, rows)`` with
    per-pass node/time stats rows shaped like ``graph_passes.optimize``'s.
    Pure ``Graph -> Graph`` like the standard pipeline — the caller owns
    caching and the off-path guarantee."""
    rows = []
    for name, version, fn in _TIER_PASSES[t]:
        t0 = time.perf_counter()
        n_in = graph.n_nodes
        graph = fn(graph, ctx)
        rows.append({"pass": name, "version": version, "nodes_in": n_in,
                     "nodes_out": graph.n_nodes,
                     "seconds": round(time.perf_counter() - t0, 6)})
    if graph.constants:
        # a later pass can supersede an earlier pass's baked constant
        # (int8 quantizing a fold-baked fp32 weight): drop constants no
        # surviving entry or head reads, so the dead fp32 copy doesn't
        # stay resident per bucket for the twin's lifetime
        used = set(graph.heads)
        for _, in_names in graph.entries:
            used.update(in_names)
        if any(k not in used for k in graph.constants):
            graph = Graph(graph.entries, graph.heads,
                          {k: v for k, v in graph.constants.items()
                           if k in used})
    return graph, rows


# -- shared plumbing ---------------------------------------------------------


class TierOp:
    """Duck-typed OpDef stand-in for tier-wrapped nodes.  Unlike
    :class:`~.ir.SynthOp` it carries the WRAPPED op's ``attr_names`` and
    ``defaults``, so ``node_call_attrs`` / ``node_attr`` / the analyzers
    keep resolving attrs exactly as they did for the original node."""

    aux = ()
    aux_update = None
    mutates = ()
    inputs_fn = None
    variadic = False
    arg_names = ()

    def __init__(self, name, fn, inner=None, attr_names=()):
        self.name = name
        self.fn = fn
        self.attr_names = tuple(getattr(inner, "attr_names", attr_names))
        self.defaults = dict(getattr(inner, "defaults", {}) or {})

    def __repr__(self):
        return "TierOp(%s)" % self.name


def _cast_fn(x, *, dtype):  # mxlint: traced
    # the explicit region-boundary convert the whole tier exists to insert
    return x.astype(dtype)  # mxlint: ignore[implicit-downcast]


_CAST_OP = SynthOp("_precision_cast", _cast_fn, attr_names=("dtype",))


def _out_dtypes(graph, ctx):
    """{env name -> numpy dtype} over ``graph`` via one abstract walk
    (args/aux from avals, constants from their values, node outputs from
    ``jax.eval_shape``) — the exact dtypes the fp32 plan lowers with."""
    from ..analysis.graph_analyzers import _abstract_walk

    dts = {}
    for n, av in list(ctx.arg_avals.items()) + list(ctx.aux_avals.items()):
        dts[n] = np.dtype(av.dtype)
    for n, v in graph.constants.items():
        dts[n] = np.asarray(v).dtype

    def record(node, nm, shape, dtype, in_vals, in_names):
        dts[nm] = np.dtype(dtype)

    _abstract_walk(graph, ctx, record=record)
    return dts


def _consumers(graph):
    """{env name -> number of reads} across entries + heads."""
    n = {}
    for _, in_names in graph.entries:
        for nm in in_names:
            n[nm] = n.get(nm, 0) + 1
    for h in graph.heads:
        n[h] = n.get(h, 0) + 1
    return n


def _producers(graph):
    """{out env name -> (node, in_names)}."""
    out = {}
    for node, in_names in graph.entries:
        for nm in node_out_names(node):
            out[nm] = (node, in_names)
    return out


# -- pass 1: conv/FC weight folding ------------------------------------------

# producers whose output-channel axis is weight axis 0 and whose data
# layout puts channels on axis 1 of the output (the _bn_affine axis=1
# shape) — the only geometry the fold handles
_FOLDABLE = ("Convolution", "FullyConnected")


def _channel_first(node):
    opname = node.op.name
    if opname == "FullyConnected":
        # flattened output is (N, num_hidden): channel axis 1
        return node_attr(node, "flatten", True)
    layout = node_attr(node, "layout")
    return layout is None or (len(layout) > 1 and layout[1] == "C")


def fold_conv_affine(graph, ctx):
    """Fold ``_bn_affine`` scale/shift into the preceding Convolution /
    FullyConnected weights (plan-time, via baked constants); the affine
    node is deleted and its consumers re-point at the producer."""
    prods = _producers(graph)
    uses = _consumers(graph)
    rename = {}
    entries = []
    consts = dict(graph.constants)
    replaced = {}  # producer node name -> (PlanNode, new in_names)
    dropped = set()  # entry indices of folded affine nodes

    for idx, (node, in_names) in enumerate(graph.entries):
        if getattr(node.op, "name", "") != "_bn_affine" \
                or node.num_outputs != 1 or len(in_names) != 5:
            continue
        data_nm = in_names[0]
        prod = prods.get(data_nm)
        if prod is None:
            continue
        pnode, pin = prod
        if getattr(pnode.op, "name", "") not in _FOLDABLE \
                or pnode.num_outputs != 1 or not _channel_first(pnode) \
                or pnode.name in replaced:
            continue
        if uses.get(data_nm, 0) != 1:
            # another consumer reads the un-affined producer output —
            # folding would change what it sees
            continue
        ax = node_attr(node, "axis", 1)
        # the fold rescales weight axis 0 = the producer's CHANNEL axis:
        # conv outputs are N-D channel-first, where only axis 1 is
        # channels (-1 would be the trailing spatial dim — and can pass
        # the length guard whenever C_out equals it); FC outputs are 2-D
        # (N, num_hidden), where 1 and -1 coincide
        if getattr(pnode.op, "name", "") == "FullyConnected":
            if ax not in (1, -1):
                continue
        elif ax != 1:
            continue
        vals = [ctx.value_of(graph, nm) for nm in in_names[1:]]
        if any(v is None for v in vals):
            continue  # unbound affine params: leave the node in place
        if len(pin) < 2:
            continue
        w = ctx.value_of(graph, pin[1])
        if w is None:
            continue
        bias = ctx.value_of(graph, pin[2]) if len(pin) > 2 else None
        if len(pin) > 2 and bias is None:
            # the producer HAS a bias but it is a runtime-computed value
            # (a node output, not a bound arg/const) — folding would
            # silently drop the bias term from the twin
            continue
        gamma, beta, mean, var = (v.astype(np.float32) for v in vals)
        eps = node_attr(node, "eps", 1e-3)
        eps = 1e-3 if eps is None else float(eps)
        if node_attr(node, "fix_gamma", True):
            gamma = np.ones_like(gamma)
        scale = gamma / np.sqrt(var + eps)
        shift = beta - mean * scale
        if w.shape[0] != scale.shape[0]:
            continue  # channel mismatch (grouped exotic layout): skip
        w2 = (w.astype(np.float32)
              * scale.reshape((-1,) + (1,) * (w.ndim - 1))).astype(w.dtype)
        b2 = (bias.astype(np.float32) * scale + shift).astype(np.float32) \
            if bias is not None else shift.astype(np.float32)
        wc, bc = "%s__folded_weight" % pnode.name, \
            "%s__folded_bias" % pnode.name
        consts[wc], consts[bc] = w2, b2
        attrs = dict(pnode.attrs)
        attrs["no_bias"] = False
        new = PlanNode(pnode.op, attrs, pnode.name)
        replaced[pnode.name] = (new, (pin[0], wc, bc))
        dropped.add(idx)
        rename["%s_output" % node.name] = data_nm
        # downstream consumers now read the affined value under the
        # producer's name — point calibration lookups at the range the
        # structural plan recorded for it (the affine output's)
        ctx.calib_alias[data_nm] = "%s_output" % node.name

    if not replaced:
        return graph
    for idx, (node, in_names) in enumerate(graph.entries):
        if idx in dropped:
            continue
        if node.name in replaced and getattr(node.op, "name", "") \
                in _FOLDABLE:
            node, in_names = replaced[node.name]
        entries.append((node, tuple(rename.get(n, n) for n in in_names)))
    return Graph(entries, (rename.get(h, h) for h in graph.heads), consts)


# -- pass 2: the bf16 cast pass ----------------------------------------------

_F32 = np.dtype("float32")
_BF16 = "bfloat16"

# contractions for which ops/nn.py grew explicit fp32 accumulation
# (``accum_dtype``): bf16 operands, preferred_element_type=float32, output
# re-narrowed in-op.  Other fp32_accum ops go through the fp32 island.
_ACCUM_DTYPE_OPS = ("Convolution", "FullyConnected")


def _island_fn(inner):
    """The fp32-island wrapper: low-precision float operands upcast to
    fp32 INSIDE the op, the reduction/cancellation chain runs entirely in
    fp32, and float outputs re-narrow to bf16 at the exit — the jaxpr
    shows convert(f32) -> reduce(f32) -> convert(bf16), which is the
    verifiable "keep an fp32 accumulator" contract."""

    def fn(*args, **attrs):  # mxlint: traced
        import jax.numpy as jnp

        up = [a.astype(jnp.float32)
              if getattr(getattr(a, "dtype", None), "itemsize", 4) <= 2
              and jnp.issubdtype(getattr(a, "dtype", np.float32),
                                 jnp.floating) else a
              for a in args]
        res = inner.fn(*up, **attrs)
        outs = res if isinstance(res, tuple) else (res,)
        outs = tuple(
            o.astype(jnp.bfloat16)  # mxlint: ignore[implicit-downcast]
            if jnp.issubdtype(o.dtype, jnp.floating) else o for o in outs)
        return outs if isinstance(res, tuple) else outs[0]

    return fn


def bf16_cast(graph, ctx):
    """The CastPlan consumer (module docstring): bf16_safe regions run
    bf16, fp32_accum keeps fp32 accumulation, fp32_only stays untouched;
    one cast node max per (value, direction); heads re-widen."""
    verdicts = {r["node"]: r["verdict"] for r in ctx.cast_plan.rows}
    dts = _out_dtypes(graph, ctx)
    holds = {}        # env name -> "bf16" when the rewritten plan narrowed it
    casts = {}        # (env name, want) -> cast output env name
    entries = []

    def request(nm, want):
        """Env name providing ``nm``'s value in ``want`` ("bf16"|"f32");
        inserts (and caches) at most one cast node per direction."""
        if dts.get(nm) != _F32:
            return nm  # non-f32 values never participate
        have = holds.get(nm, "f32")
        if have == want:
            return nm
        key = (nm, want)
        hit = casts.get(key)
        if hit is not None:
            return hit
        dtype = _BF16 if want == "bf16" else "float32"
        cnode = PlanNode(_CAST_OP, {"dtype": dtype},
                         "%s__to_%s" % (nm, want))
        out = node_out_names(cnode)[0]
        entries.append((cnode, (nm,)))
        casts[key] = out
        return out

    for node, in_names in graph.entries:
        opname = getattr(node.op, "name", "")
        verdict = verdicts.get(node.name)
        out_nm = node_out_names(node)
        # a node with no float32 operand to narrow (e.g. a surviving
        # random_* source) must stay untouched: its output would remain
        # f32 while the bookkeeping claimed bf16, and a downstream
        # contraction would see mixed operand dtypes
        has_f32_in = any(dts.get(n) == _F32 for n in in_names)
        rewriteable = (verdict in ("bf16_safe", "fp32_accum")
                       and node.num_outputs == 1 and has_f32_in
                       and dts.get(out_nm[0]) == _F32
                       and opname != "_precision_cast")
        if not rewriteable:
            # fp32_only / unknown / non-f32: the node must see the fp32
            # plan's operand dtypes — re-widen anything a safe region
            # narrowed upstream
            entries.append((node, tuple(request(n, "f32")
                                        for n in in_names)))
            continue
        if verdict == "bf16_safe":
            entries.append((node, tuple(request(n, "bf16")
                                        for n in in_names)))
        elif opname in _ACCUM_DTYPE_OPS:
            attrs = dict(node.attrs)
            attrs["accum_dtype"] = "float32"
            attrs["out_dtype"] = _BF16
            entries.append((PlanNode(node.op, attrs, node.name),
                            tuple(request(n, "bf16") for n in in_names)))
        else:
            # fp32 island: operands feed through AS HELD (no boundary cast
            # nodes) — an fp32 original enters untouched, a bf16 region
            # value upcasts inside the wrapper, so the island adds zero
            # graph-level converts either way
            entries.append((PlanNode(
                TierOp("_fp32_island", _island_fn(node.op), inner=node.op),
                dict(node.attrs), node.name, node.num_outputs), in_names))
        holds[out_nm[0]] = "bf16"

    heads = tuple(request(h, "f32") for h in graph.heads)
    if not holds and not casts:
        return graph
    return Graph(entries, heads, graph.constants)


# -- pass 3: calibration-based int8 rewrite ----------------------------------


class CalibrationTable:
    """Observed per-tensor ranges from :func:`calibrate` — ``{env name ->
    (lo, hi)}`` plus the batch count, fingerprinted so an int8 twin's AOT
    key moves when (and only when) the calibration data moves."""

    __slots__ = ("ranges", "batches")

    def __init__(self, ranges, batches=0):
        self.ranges = {str(k): (float(lo), float(hi))
                       for k, (lo, hi) in ranges.items()}
        self.batches = int(batches)

    def range(self, name):
        return self.ranges.get(name)

    def fingerprint(self):
        blob = json.dumps(
            {k: [round(v[0], 6), round(v[1], 6)]
             for k, v in sorted(self.ranges.items())}, sort_keys=True)
        return "calib-" + hashlib.sha256(blob.encode()).hexdigest()[:16]

    def __repr__(self):
        return "CalibrationTable(%d tensors, %d batches, %s)" % (
            len(self.ranges), self.batches, self.fingerprint())


def calibrate(predictor, batches):
    """Record per-tensor min/max over ``batches`` (iterable of
    ``{input name -> array}``) through the predictor's STRUCTURAL eval plan
    (tier passes excluded — calibration describes the fp32 graph the int8
    rewrite will replace) -> :class:`CalibrationTable`.

    This is the runtime refinement of the numerics interval analysis: the
    static transfer functions bound what they can prove, this records what
    the deployment's data actually produces.  Evaluation is eager jax on
    the bound executor (no jit, no plan mutation); feed O(10) representative
    batches — the table's maxabs drives every activation scale."""
    from .ir import node_call_attrs

    exe = predictor._exec
    plan, _heads, const_env = exe._structural_plan(False)
    import jax

    key = jax.random.PRNGKey(0)
    lo, hi = {}, {}

    def note(nm, v):
        arr = np.asarray(v)
        if arr.dtype.kind != "f" or arr.size == 0:
            return
        l, h = float(arr.min()), float(arr.max())
        if np.isnan(l) or np.isnan(h):
            return
        lo[nm] = min(lo.get(nm, l), l)
        hi[nm] = max(hi.get(nm, h), h)

    n_batches = 0
    for batch in batches:
        n_batches += 1
        env = dict(const_env) if const_env else {}
        for n, a in exe.arg_dict.items():
            env[n] = a._data
        for n, a in exe.aux_dict.items():
            env[n] = a._data
        for n, v in batch.items():
            env[n] = np.asarray(v, np.float32)
        for node, in_names in plan:
            attrs = node_call_attrs(node, key, False)
            res = node.op.fn(*[env[n] for n in in_names], **attrs)
            outs = res if isinstance(res, tuple) else (res,)
            if len(outs) > 1 and node.num_outputs == 1:
                outs = outs[:1]
            for nm, o in zip(node_out_names(node), outs):
                env[nm] = o
        for nm, v in env.items():
            note(nm, v)
    return CalibrationTable({k: (lo[k], hi[k]) for k in lo},
                            batches=n_batches)


def observe_ranges(predictor, batch, names):
    """Live (lo, hi) for a subset of STRUCTURAL env names on one batch —
    the quality plane's drift hook (telemetry/qualityplane.py): the same
    eager structural-plan walk :func:`calibrate` does, restricted to the
    names int8 sites quantize, so a shadow-sampled batch can be compared
    against the baked :class:`CalibrationTable` without touching the
    compiled twin.  Runs off the reply path (shadow thread only).
    Returns ``{name -> (lo, hi)}`` for the names actually produced."""
    from .ir import node_call_attrs

    want = set(names)
    if not want:
        return {}
    exe = predictor._exec
    plan, _heads, const_env = exe._structural_plan(False)
    import jax

    key = jax.random.PRNGKey(0)
    env = dict(const_env) if const_env else {}
    for n, a in exe.arg_dict.items():
        env[n] = a._data
    for n, a in exe.aux_dict.items():
        env[n] = a._data
    for n, v in batch.items():
        env[n] = np.asarray(v, np.float32)
    out = {}

    def note(nm):
        arr = np.asarray(env[nm])
        if arr.dtype.kind != "f" or arr.size == 0:
            return
        l, h = float(arr.min()), float(arr.max())
        if not (np.isnan(l) or np.isnan(h)):
            out[nm] = (l, h)

    for nm in want & set(env):
        note(nm)
    pending = want - set(out)
    for node, in_names in plan:
        if not pending:
            break
        attrs = node_call_attrs(node, key, False)
        res = node.op.fn(*[env[n] for n in in_names], **attrs)
        outs = res if isinstance(res, tuple) else (res,)
        if len(outs) > 1 and node.num_outputs == 1:
            outs = outs[:1]
        for nm, o in zip(node_out_names(node), outs):
            env[nm] = o
            if nm in pending:
                note(nm)
                pending.discard(nm)
    return out


def _int8_conv_fn(data, wq, wscale, bias=None, **attrs):  # mxlint: traced
    """Symmetric int8 conv: quantize the activation per-tensor, integer
    conv with int32 accumulation (the quantized_conv.cc shape —
    ops/quantization.py), fp32 dequant by a_scale * per-channel w_scale."""
    import jax
    import jax.numpy as jnp

    from ..ops.nn import _tup

    a_scale = attrs["a_scale"]
    s = _tup(attrs.get("stride"), 2)
    d = _tup(attrs.get("dilate"), 2)
    p = _tup(attrs.get("pad") if attrs.get("pad") is not None else 0, 2)
    xq = jnp.clip(jnp.round(data / a_scale), -127.0, 127.0) \
        .astype(jnp.int8)  # mxlint: ignore[implicit-downcast]
    out32 = jax.lax.conv_general_dilated(
        xq.astype(jnp.int32), wq.astype(jnp.int32), window_strides=s,
        padding=[(pi, pi) for pi in p], rhs_dilation=d,
        feature_group_count=attrs.get("num_group", 1),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    out = out32.astype(jnp.float32) * (a_scale * wscale.reshape(1, -1, 1, 1))
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def _int8_fc_fn(data, wq, wscale, bias=None, **attrs):  # mxlint: traced
    """Symmetric int8 dense: per-tensor activation scale, per-channel
    weight scales, int32 accumulation, fp32 dequant."""
    import jax
    import jax.numpy as jnp

    a_scale = attrs["a_scale"]
    x = data.reshape(data.shape[0], -1) if attrs.get("flatten", True) \
        else data
    xq = jnp.clip(jnp.round(x / a_scale), -127.0, 127.0) \
        .astype(jnp.int8)  # mxlint: ignore[implicit-downcast]
    out32 = jax.lax.dot_general(
        xq.astype(jnp.int32), wq.astype(jnp.int32),
        (((x.ndim - 1,), (1,)), ((), ())))
    out = out32.astype(jnp.float32) * (a_scale * wscale)
    if bias is not None:
        out = out + bias
    return out


def int8_rewrite(graph, ctx):
    """Rewrite calibrated Convolution/FullyConnected nodes to int8 compute
    (module docstring).  Coverage rules: data input calibrated, weight
    value baked-able, verdict not fp32_only — everything else untouched."""
    if ctx.calibration is None:
        return graph
    verdicts = {r["node"]: r["verdict"] for r in ctx.cast_plan.rows}
    dts = _out_dtypes(graph, ctx)
    consts = dict(graph.constants)
    entries = []
    changed = False

    for node, in_names in graph.entries:
        opname = getattr(node.op, "name", "")
        verdict = verdicts.get(node.name)
        ok = (opname in _FOLDABLE and node.num_outputs == 1
              and verdict is not None and verdict != "fp32_only"
              and _channel_first(node) and len(in_names) >= 2
              and dts.get(in_names[0]) == _F32)
        if ok and opname == "Convolution":
            kern = node_attr(node, "kernel")
            ok = kern is not None and len(tuple(np.atleast_1d(kern))) == 2 \
                and node_attr(node, "layout") in (None, "NCHW")
        # ranges recorded on the structural plan, resolved through any
        # fold rename — a conv/FC fed by a folded BN quantizes with the
        # AFFINED activation range, not the pre-BN one
        rng = ctx.calib_range(in_names[0]) if ok else None
        w = ctx.value_of(graph, in_names[1]) if ok else None
        if not ok or rng is None or w is None:
            entries.append((node, in_names))
            continue
        a_max = max(abs(rng[0]), abs(rng[1]))
        if not np.isfinite(a_max) or a_max <= 0.0:
            entries.append((node, in_names))
            continue
        a_scale = float(a_max / 127.0)
        # drift-hook export: the quality plane observes live ranges on
        # the STRUCTURAL plan, so record the alias-resolved input name
        # the calibrated range was keyed under
        ctx.int8_sites[node.name] = {
            "input": ctx.calib_alias.get(in_names[0], in_names[0]),
            "lo": float(rng[0]), "hi": float(rng[1]), "a_scale": a_scale}
        wf = w.astype(np.float32)
        chan_max = np.abs(wf).reshape(wf.shape[0], -1).max(axis=1)
        chan_max = np.where(chan_max > 0, chan_max, 1.0)
        w_scale = (chan_max / 127.0).astype(np.float32)
        wq = np.clip(
            np.round(wf / w_scale.reshape((-1,) + (1,) * (wf.ndim - 1))),
            -127, 127).astype(np.int8)
        wc = "%s__int8_weight" % node.name
        sc = "%s__int8_scale" % node.name
        consts[wc], consts[sc] = wq, w_scale
        fn = _int8_conv_fn if opname == "Convolution" else _int8_fc_fn
        op = TierOp("_int8_%s" % opname.lower(), fn, inner=node.op)
        op.attr_names = tuple(op.attr_names) + ("a_scale",)
        attrs = dict(node.attrs)
        attrs["a_scale"] = a_scale
        new_in = (in_names[0], wc, sc) + tuple(in_names[2:3])
        entries.append((PlanNode(op, attrs, node.name), new_in))
        changed = True

    if not changed:
        return graph
    return Graph(entries, graph.heads, consts)


_TIER_PASSES = {
    "bf16": (("fold_conv_affine", 1, fold_conv_affine),
             ("bf16_cast", 1, bf16_cast)),
    "int8": (("fold_conv_affine", 1, fold_conv_affine),
             ("int8_rewrite", 1, int8_rewrite)),
}
