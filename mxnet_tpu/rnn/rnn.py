"""RNN checkpoint helpers — reference ``python/mxnet/rnn/rnn.py``."""
from __future__ import annotations

from .. import model
from ..base import MXNetError

__all__ = ["rnn_unroll", "save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]


def rnn_unroll(cell, length, inputs=None, begin_state=None, input_prefix="", layout="NTC"):
    """Deprecated alias for cell.unroll (reference rnn.py:26); with
    inputs=None, per-step input Variables are auto-created as in the
    reference."""
    if inputs is None:
        from .. import symbol

        inputs = [symbol.Variable("%st%d_data" % (input_prefix, i)) for i in range(length)]
    return cell.unroll(length, inputs=inputs, begin_state=begin_state, layout=layout)


def _normalize_cells(cells):
    if not isinstance(cells, (list, tuple)):
        cells = [cells]
    return cells


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Saves checkpoint with fused weights unpacked (reference rnn.py:32)."""
    for cell in _normalize_cells(cells):
        arg_params = cell.unpack_weights(arg_params)
    model.save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Loads checkpoint, re-packing weights for the cells (reference rnn.py:62)."""
    sym, arg, aux = model.load_checkpoint(prefix, epoch)
    for cell in _normalize_cells(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end checkpoint callback (reference rnn.py:97)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
