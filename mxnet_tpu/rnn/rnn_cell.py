"""Symbol-based RNN cells — reference ``python/mxnet/rnn/rnn_cell.py``
(BaseRNNCell :108, RNNCell :362, LSTMCell :408, GRUCell :469, FusedRNNCell
:536, SequentialRNNCell :748, DropoutCell :827, ModifierCell :867,
ZoneoutCell :909, ResidualCell :957, BidirectionalCell :998).

TPU note: unrolling builds a static symbol graph that jits into one XLA
computation; FusedRNNCell emits the registry's fused ``RNN`` op whose inner
time loop is a ``lax.scan`` (ops/rnn.py) — the cuDNN-fused analog.
Conv*RNN cells are not ported (niche; use gluon.rnn or compose manually).
"""
from __future__ import annotations

import numpy as np

from .. import symbol
from ..symbol import Symbol
from ..base import MXNetError
from ..ndarray.ndarray import array as _nd_array


def _np(v):
    return v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)


class _DeferredZeros:
    """Unknown-batch zero state (the reference's shape-0 convention,
    rnn_cell.py:108 begin_state).  Concrete init ops can't carry a symbolic
    batch dim, so begin_state(func=sym.zeros) with a 0 in the shape yields
    this placeholder; unroll resolves it to ``_zeros_rows`` against the
    actual sequence inputs."""

    def __init__(self, name, shape, dtype=None):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype

    def resolve(self, batch_ref):
        """batch_ref: a Symbol whose axis 0 is the batch dimension."""
        bidx = self.shape.index(0)
        tail = tuple(s for i, s in enumerate(self.shape) if i != bidx)
        kw = {} if self.dtype is None else {"dtype": self.dtype}
        z = symbol._zeros_rows(batch_ref, tail=tail, name=self.name, **kw)
        if bidx:
            ndim = len(self.shape)
            axes = tuple(list(range(1, bidx + 1)) + [0] + list(range(bidx + 1, ndim)))
            z = symbol.transpose(z, axes=axes)
        return z


def _resolve_states(states, batch_ref):
    return [s.resolve(batch_ref) if isinstance(s, _DeferredZeros) else s for s in states]

__all__ = [
    "RNNParams",
    "BaseRNNCell",
    "RNNCell",
    "LSTMCell",
    "GRUCell",
    "FusedRNNCell",
    "SequentialRNNCell",
    "DropoutCell",
    "ModifierCell",
    "ZoneoutCell",
    "ResidualCell",
    "BidirectionalCell",
]


class RNNParams:
    """Container for cell parameter symbols (reference rnn_cell.py:78)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """(reference rnn_cell.py:51) Returns (list-or-merged inputs, axis)."""
    assert inputs is not None
    axis = layout.find("T")
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, Symbol):
        if merge is False:
            if len(inputs.list_outputs()) != 1:
                raise MXNetError("unroll doesn't allow grouped symbol as input")
            inputs = list(
                symbol.split(inputs, axis=in_axis, num_outputs=length, squeeze_axis=1)
            )
    else:
        assert length is None or len(inputs) == length
        if merge is True:
            inputs = [symbol.expand_dims(i, axis=axis) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=axis)
    if isinstance(inputs, Symbol) and axis != in_axis:
        perm = [0, 1, 2]
        perm[axis], perm[in_axis] = perm[in_axis], perm[axis]
        inputs = symbol.transpose(inputs, axes=tuple(perm))
    return inputs, axis


class BaseRNNCell:
    """Abstract RNN cell (reference rnn_cell.py:108)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, batch_ref=None, **kwargs):
        """Initial states.  With ``batch_ref`` (a Symbol carrying the batch
        dim on axis 0) states are batch-dynamic zeros; otherwise they are
        bindable Variables with partial shape hints (the reference's shape-0
        convention)."""
        assert not self._modified, "After applying modifier cells the base cell cannot be called directly."
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is not None:
                kw = dict(kwargs)
                if info is not None:
                    kw.update(info)
                shape = kw.get("shape")
                if shape is not None and any(s == 0 for s in shape):
                    # reference shape-0 = unknown batch; only zeros can be
                    # deferred to bind time here
                    if func is symbol.zeros or getattr(func, "__name__", "") == "zeros":
                        state = _DeferredZeros(name, shape, dtype=kw.get("dtype"))
                    else:
                        raise MXNetError(
                            "begin_state func=%r got partial shape %s (0 = unknown "
                            "batch). Only sym.zeros supports deferred batch; pass a "
                            "fully-specified shape or use begin_state() inside "
                            "unroll." % (func, (shape,))
                        )
                else:
                    state = func(name=name, **kw)
            elif batch_ref is not None:
                tail = tuple(info["shape"][1:])
                state = symbol._zeros_rows(batch_ref, tail=tail, name=name)
            else:
                v = symbol.Variable(name)
                v._shape_hint = tuple(info["shape"])
                state = v
            states.append(state)
        return states

    def unpack_weights(self, args):
        """Fused-format -> per-gate weights (reference :232); identity for
        unfused cells with per-gate names."""
        args = dict(args)
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ["i2h", "h2h"]:
            weight = args.pop("%s%s_weight" % (self._prefix, group_name))
            bias = args.pop("%s%s_bias" % (self._prefix, group_name))
            w = _np(weight)
            b = _np(bias)
            for j, gate in enumerate(self._gate_names):
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                args[wname] = _nd_array(w[j * h : (j + 1) * h].copy())
                args[bname] = _nd_array(b[j * h : (j + 1) * h].copy())
        return args

    def pack_weights(self, args):
        """Inverse of unpack_weights (reference :252)."""
        args = dict(args)
        if not self._gate_names:
            return args
        for group_name in ["i2h", "h2h"]:
            ws, bs = [], []
            for gate in self._gate_names:
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                w = args.pop(wname)
                b = args.pop(bname)
                ws.append(_np(w))
                bs.append(_np(b))
            args["%s%s_weight" % (self._prefix, group_name)] = _nd_array(np.concatenate(ws))
            args["%s%s_bias" % (self._prefix, group_name)] = _nd_array(np.concatenate(bs))
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None):
        """Unrolls the cell for ``length`` steps (reference :276)."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(batch_ref=inputs[0])
        states = _resolve_states(begin_state, inputs[0])
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout, merge_outputs)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """Elman RNN cell (reference rnn_cell.py:362)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        states = _resolve_states(states, inputs)
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(
            data=inputs, weight=self._iW, bias=self._iB,
            num_hidden=self._num_hidden, name="%si2h" % name,
        )
        h2h = symbol.FullyConnected(
            data=states[0], weight=self._hW, bias=self._hB,
            num_hidden=self._num_hidden, name="%sh2h" % name,
        )
        output = self._get_activation(i2h + h2h, self._activation, name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference rnn_cell.py:408)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None, forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        from ..initializer import LSTMBias

        self._iB = self.params.get("i2h_bias", init=LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [
            {"shape": (0, self._num_hidden), "__layout__": "NC"},
            {"shape": (0, self._num_hidden), "__layout__": "NC"},
        ]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        states = _resolve_states(states, inputs)
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(
            data=inputs, weight=self._iW, bias=self._iB,
            num_hidden=self._num_hidden * 4, name="%si2h" % name,
        )
        h2h = symbol.FullyConnected(
            data=states[0], weight=self._hW, bias=self._hB,
            num_hidden=self._num_hidden * 4, name="%sh2h" % name,
        )
        gates = i2h + h2h
        slices = list(symbol.SliceChannel(gates, num_outputs=4, name="%sslice" % name))
        in_gate = symbol.Activation(slices[0], act_type="sigmoid")
        forget_gate = symbol.Activation(slices[1], act_type="sigmoid")
        in_transform = symbol.Activation(slices[2], act_type="tanh")
        out_gate = symbol.Activation(slices[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference rnn_cell.py:469)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        states = _resolve_states(states, inputs)
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = symbol.FullyConnected(
            data=inputs, weight=self._iW, bias=self._iB,
            num_hidden=self._num_hidden * 3, name="%si2h" % name,
        )
        h2h = symbol.FullyConnected(
            data=prev_h, weight=self._hW, bias=self._hB,
            num_hidden=self._num_hidden * 3, name="%sh2h" % name,
        )
        i2h_r, i2h_z, i2h = list(symbol.SliceChannel(i2h, num_outputs=3))
        h2h_r, h2h_z, h2h = list(symbol.SliceChannel(h2h, num_outputs=3))
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = symbol.Activation(i2h + reset_gate * h2h, act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN over the registry ``RNN`` op (reference :536;
    the cuDNN path — here a lax.scan kernel, ops/rnn.py)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm", bidirectional=False,
                 dropout=0.0, get_next_state=False, forget_bias=1.0,
                 prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._parameter = self.params.get("parameters")

    @property
    def state_info(self):
        b = self._bidirectional + 1
        n = (self._mode == "lstm") + 1
        return [
            {"shape": (b * self._num_layers, 0, self._num_hidden), "__layout__": "LNC"}
            for _ in range(n)
        ]

    @property
    def _gate_names(self):
        return {
            "rnn_relu": [""],
            "rnn_tanh": [""],
            "lstm": ["_i", "_f", "_c", "_o"],
            "gru": ["_r", "_z", "_o"],
        }[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def begin_state(self, func=None, batch_ref=None, **kwargs):
        if batch_ref is None or func is not None:
            return super().begin_state(func=func, batch_ref=batch_ref, **kwargs)
        # batch axis is axis 1 here (LNC) — build (L, N, C) zeros from ref
        states = []
        b = self._bidirectional + 1
        n = (self._mode == "lstm") + 1
        for i in range(n):
            z = symbol._zeros_rows(
                batch_ref, tail=(b * self._num_layers, self._num_hidden)
            )
            states.append(symbol.transpose(z, axes=(1, 0, 2)))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, True)
        if axis == 1:  # NTC -> TNC for the RNN op
            inputs = symbol.transpose(inputs, axes=(1, 0, 2))
        batch_ref_nc = symbol.transpose(inputs, axes=(1, 0, 2))
        if begin_state is None:
            begin_state = self.begin_state(batch_ref=batch_ref_nc)
        states = _resolve_states(begin_state, batch_ref_nc)
        kwargs = {}
        if self._mode == "lstm":
            kwargs["state_cell"] = states[1]
        rnn = symbol.RNN(
            data=inputs,
            parameters=self._parameter,
            state=states[0],
            mode=self._mode,
            state_size=self._num_hidden,
            num_layers=self._num_layers,
            bidirectional=self._bidirectional,
            p=self._dropout,
            state_outputs=self._get_next_state,
            name="%srnn" % self._prefix,
            **kwargs,
        )
        if not self._get_next_state:
            outputs, states = rnn[0], []
        elif self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]
        if layout == "NTC":
            outputs = symbol.transpose(outputs, axes=(1, 0, 2))
        if merge_outputs is False:
            outputs = list(
                symbol.split(outputs, axis=layout.find("T"), num_outputs=length, squeeze_axis=1)
            )
        return outputs, states

    def _slot_names(self):
        """Per-(layer, direction) unfused prefixes, enumeration order matching
        the fused vector (ops/rnn.py _unpack_params)."""
        names = []
        for i in range(self._num_layers):
            if self._bidirectional:
                names.append("%sl%d_" % (self._prefix, i))
                names.append("%sr%d_" % (self._prefix, i))
            else:
                names.append("%sl%d_" % (self._prefix, i))
        return names

    def unpack_weights(self, args):
        """Fused parameter vector -> per-layer i2h/h2h arrays matching
        unfuse() naming (reference FusedRNNCell.unpack_weights :621)."""
        args = dict(args)
        p = args.pop("%sparameters" % self._prefix)
        p = _np(p)
        h = self._num_hidden
        g = self._num_gates
        d = 2 if self._bidirectional else 1
        L = self._num_layers
        rest = (L - 1) * d * g * h * (d * h + h + 2)
        isz = (p.size - rest) // (d * g * h) - h - 2
        slots = self._slot_names()
        pos = 0
        for li, slot in enumerate(slots):
            layer = li // d
            in_size = isz if layer == 0 else d * h
            wi = p[pos : pos + g * h * in_size].reshape(g * h, in_size)
            pos += g * h * in_size
            wh = p[pos : pos + g * h * h].reshape(g * h, h)
            pos += g * h * h
            args[slot + "i2h_weight"] = _nd_array(wi.copy())
            args[slot + "h2h_weight"] = _nd_array(wh.copy())
        for slot in slots:
            args[slot + "i2h_bias"] = _nd_array(p[pos : pos + g * h].copy())
            pos += g * h
            args[slot + "h2h_bias"] = _nd_array(p[pos : pos + g * h].copy())
            pos += g * h
        return args

    def pack_weights(self, args):
        """Inverse of unpack_weights (reference :652)."""
        args = dict(args)
        slots = self._slot_names()
        chunks = []
        for slot in slots:
            wi = args.pop(slot + "i2h_weight")
            wh = args.pop(slot + "h2h_weight")
            chunks.append(_np(wi).ravel())
            chunks.append(_np(wh).ravel())
        for slot in slots:
            bi = args.pop(slot + "i2h_bias")
            bh = args.pop(slot + "h2h_bias")
            chunks.append(_np(bi).ravel())
            chunks.append(_np(bh).ravel())
        args["%sparameters" % self._prefix] = _nd_array(np.concatenate(chunks))
        return args

    def unfuse(self):
        """Equivalent stack of unfused cells (reference :676)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden, activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden, activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(
                    BidirectionalCell(
                        get_cell("%sl%d_" % (self._prefix, i)),
                        get_cell("%sr%d_" % (self._prefix, i)),
                        output_prefix="%sbi_l%d_" % (self._prefix, i),
                    )
                )
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout, prefix="%s_dropout%d_" % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stacks cells (reference rnn_cell.py:748)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []
        self._override_cell_params = params is not None

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            state = states[p : p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None):
        self.reset()
        num_cells = len(self._cells)
        if begin_state is None:
            inputs_list, _ = _normalize_sequence(length, inputs, layout, False)
            begin_state = self.begin_state(batch_ref=inputs_list[0])
            inputs = inputs_list
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p : p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs,
            )
            next_states.extend(states)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Dropout on outputs (reference rnn_cell.py:827)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None):
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, merge_outputs)
        if isinstance(inputs, Symbol):
            return self(inputs, begin_state if begin_state is not None else [])
        return super().unroll(
            length, inputs, begin_state=begin_state, layout=layout, merge_outputs=merge_outputs
        )


class ModifierCell(BaseRNNCell):
    """Wraps a cell to modify its behavior (reference rnn_cell.py:867)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, batch_ref=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, batch_ref=batch_ref, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference rnn_cell.py:909)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), "FusedRNNCell doesn't support zoneout."
        assert not isinstance(base_cell, BidirectionalCell), "BidirectionalCell doesn't support zoneout since it doesn't support step."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = self.base_cell, self.zoneout_outputs, self.zoneout_states
        states = _resolve_states(states, inputs)
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return symbol.Dropout(data=symbol.ones_like(like), p=p)

        prev_output = self.prev_output if self.prev_output is not None else symbol.zeros_like(next_output)
        output = (
            symbol.where(mask(p_outputs, next_output), next_output, prev_output)
            if p_outputs != 0.0
            else next_output
        )
        states = (
            [symbol.where(mask(p_states, new_s), new_s, old_s) for new_s, old_s in zip(next_states, states)]
            if p_states != 0.0
            else next_states
        )
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Adds input to output (reference rnn_cell.py:957)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs)
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs,
        )
        self.base_cell._modified = True
        merge_outputs = isinstance(outputs, Symbol) if merge_outputs is None else merge_outputs
        inputs, _ = _normalize_sequence(length, inputs, layout, merge_outputs)
        if merge_outputs:
            outputs = symbol.elemwise_add(outputs, inputs)
        else:
            outputs = [symbol.elemwise_add(o, i) for o, i in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    """Forward + backward cells over the sequence (reference rnn_cell.py:998)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError("Bidirectional cannot be stepped. Please use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(batch_ref=inputs[0])
        states = begin_state
        l_cell, r_cell = self._cells
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[: len(l_cell.state_info)],
            layout=layout, merge_outputs=merge_outputs,
        )
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info):],
            layout=layout, merge_outputs=merge_outputs,
        )
        if merge_outputs is None:
            merge_outputs = isinstance(l_outputs, Symbol) and isinstance(r_outputs, Symbol)
            l_outputs, _ = _normalize_sequence(None, l_outputs, layout, merge_outputs)
            r_outputs, _ = _normalize_sequence(None, r_outputs, layout, merge_outputs)
        if merge_outputs:
            r_outputs = symbol.reverse(r_outputs, axis=layout.find("T"))
            outputs = symbol.Concat(l_outputs, r_outputs, dim=2, name="%sout" % self._output_prefix)
        else:
            outputs = [
                symbol.Concat(l_o, r_o, dim=1, name="%st%d" % (self._output_prefix, i))
                for i, (l_o, r_o) in enumerate(zip(l_outputs, reversed(r_outputs)))
            ]
        states = l_states + r_states
        return outputs, states
