"""Legacy symbol-based RNN API — reference ``python/mxnet/rnn/``."""
from .rnn_cell import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403
from .io import *  # noqa: F401,F403

from . import rnn_cell
from . import rnn
from . import io

__all__ = rnn_cell.__all__ + rnn.__all__ + io.__all__
