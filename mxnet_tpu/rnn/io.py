"""Bucketed sequence IO.

Parity surface: ``encode_sentences`` / ``BucketSentenceIter`` from the
reference ``python/mxnet/rnn/io.py`` (behavioral contract only; the
implementation here is organised around per-bucket padded matrices with
permutation-based shuffling and fetch-time label shifting, which suits the
TPU story: every bucket length is one static-shape jit specialization, so
the iterator's job is to emit fixed-shape batches keyed by bucket length).
"""
from __future__ import annotations

import logging
import random as pyrandom

import numpy as np

from ..io import DataIter, DataBatch, DataDesc
from ..ndarray.ndarray import array

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences, vocab=None, invalid_label=-1, invalid_key="\n",
                     start_label=0):
    """Map token sequences to integer-id sequences.

    With ``vocab=None`` a fresh vocabulary is grown on the fly: ids are
    handed out in first-seen order starting at ``start_label``, and the id
    reserved for padding (``invalid_label``, bound to ``invalid_key``) is
    never assigned to a real token.  With an explicit ``vocab`` the mapping
    is closed: unseen tokens are an error.

    Returns ``(encoded, vocab)``.
    """
    if vocab is not None:
        # Closed vocabulary: pure lookup, loud failure on novel tokens.
        def lookup(tok):
            assert tok in vocab, "Unknown token %s" % tok
            return vocab[tok]
    else:
        vocab = {invalid_key: invalid_label}
        counter = [start_label]

        def lookup(tok):
            known = vocab.get(tok)
            if known is not None and (known != invalid_label or tok == invalid_key):
                return known
            nxt = counter[0]
            if nxt == invalid_label:   # padding id stays reserved
                nxt += 1
            counter[0] = nxt + 1
            vocab[tok] = nxt
            return nxt

    encoded = [[lookup(tok) for tok in sent] for sent in sentences]
    return encoded, vocab


class BucketSentenceIter(DataIter):
    """Fixed-shape batches over variable-length sequences via bucketing.

    Each bucket length becomes one jit specialization downstream (the
    reference's per-bucket executor, our per-bucket compiled step), so the
    iterator groups sentences by the smallest bucket that fits, pads each
    group into one dense ``(n_sent, bucket_len)`` matrix, and emits
    ``batch_size``-row slices tagged with ``bucket_key``.  Labels are the
    next-token shift of the data and are produced at fetch time rather than
    materialised per epoch.
    """

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            buckets = self._auto_buckets(sentences, batch_size)
        self.buckets = sorted(buckets)
        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.major_axis = layout.find("N")
        self.default_bucket_key = max(self.buckets)

        self.data = self._pack(sentences)
        self._schedule = []          # [(bucket_idx, start_row)] for one epoch
        self._cursor = 0

        full_shape = (batch_size, self.default_bucket_key)
        if self.major_axis != 0:
            full_shape = full_shape[::-1]
        self.provide_data = [DataDesc(data_name, full_shape, dtype, layout=layout)]
        self.provide_label = [DataDesc(label_name, full_shape, dtype, layout=layout)]
        self.reset()

    @staticmethod
    def _auto_buckets(sentences, batch_size):
        """One bucket per sentence length that occurs >= batch_size times."""
        freq = {}
        for sent in sentences:
            freq[len(sent)] = freq.get(len(sent), 0) + 1
        chosen = [n for n, c in sorted(freq.items()) if c >= batch_size]
        assert chosen, "no bucket holds >= batch_size sentences; pass buckets="
        return chosen

    def _pack(self, sentences):
        """Group sentences into dense padded matrices, one per bucket."""
        groups = [[] for _ in self.buckets]
        dropped = 0
        for sent in sentences:
            dest = None
            for k, blen in enumerate(self.buckets):
                if len(sent) <= blen:
                    dest = k
                    break
            if dest is None:
                dropped += 1
                continue
            groups[dest].append(sent)
        if dropped:
            logging.getLogger(__name__).warning(
                "BucketSentenceIter: dropped %d sentences longer than the "
                "largest bucket (%d)", dropped, self.buckets[-1])
        packed = []
        for blen, group in zip(self.buckets, groups):
            mat = np.full((len(group), blen), self.invalid_label, dtype=self.dtype)
            for row, sent in enumerate(group):
                mat[row, : len(sent)] = sent
            packed.append(mat)
        return packed

    def reset(self):
        """Reshuffle rows within buckets and the batch visitation order."""
        self._cursor = 0
        for k, mat in enumerate(self.data):
            self.data[k] = mat[np.random.permutation(len(mat))]
        self._schedule = [
            (k, start)
            for k, mat in enumerate(self.data)
            for start in range(0, len(mat) - self.batch_size + 1, self.batch_size)
        ]
        pyrandom.shuffle(self._schedule)

    def next(self):
        if self._cursor >= len(self._schedule):
            raise StopIteration
        k, start = self._schedule[self._cursor]
        self._cursor += 1
        rows = self.data[k][start : start + self.batch_size]
        # next-token target: shift left, pad the final step
        tail = np.full((rows.shape[0], 1), self.invalid_label, dtype=rows.dtype)
        labels = np.concatenate([rows[:, 1:], tail], axis=1)
        if self.major_axis != 0:   # time-major layout
            rows, labels = rows.T, labels.T
        return DataBatch(
            [array(rows)],
            [array(labels)],
            pad=0,
            bucket_key=self.buckets[k],
            provide_data=[DataDesc(self.data_name, rows.shape, self.dtype, layout=self.layout)],
            provide_label=[DataDesc(self.label_name, labels.shape, self.dtype, layout=self.layout)],
        )
