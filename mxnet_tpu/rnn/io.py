"""Bucketed sequence IO — reference ``python/mxnet/rnn/io.py``
(encode_sentences :30, BucketSentenceIter :78)."""
from __future__ import annotations

import bisect
import random as pyrandom

import numpy as np

from ..io import DataIter, DataBatch, DataDesc
from ..ndarray.ndarray import array

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences, vocab=None, invalid_label=-1, invalid_key="\n",
                     start_label=0):
    """Token lists -> id lists, building/extending vocab (reference :30)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                assert new_vocab, "Unknown token %s" % word
                if idx == invalid_label:
                    idx += 1
                vocab[word] = idx
                idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Bucketed iterator over encoded sentences (reference :78).

    Pads each sentence up to its bucket length; yields batches whose
    ``bucket_key`` is the bucket length (pairs with BucketingModule — on TPU
    each bucket is one jit specialization, the reference's per-bucket
    executor).
    """

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            buckets = [
                i for i, j in enumerate(np.bincount([len(s) for s in sentences]))
                if j >= batch_size
            ]
        buckets.sort()
        ndiscard = 0
        self.data = [[] for _ in buckets]
        for sent in sentences:
            buck = bisect.bisect_left(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[: len(sent)] = sent
            self.data[buck].append(buff)
        # empty buckets must stay 2-D (0, bucket_len) so reset()'s label
        # shift and batching indexing stay valid
        self.data = [
            np.asarray(i, dtype=dtype).reshape(len(i), buckets[k])
            for k, i in enumerate(self.data)
        ]
        if ndiscard:
            print("WARNING: discarded %d sentences longer than the largest bucket." % ndiscard)

        self.batch_size = batch_size
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.nddata = []
        self.ndlabel = []
        self.major_axis = layout.find("N")
        self.layout = layout
        self.default_bucket_key = max(buckets)

        shape = (
            (batch_size, self.default_bucket_key)
            if self.major_axis == 0
            else (self.default_bucket_key, batch_size)
        )
        self.provide_data = [DataDesc(data_name, shape, dtype, layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, dtype, layout=layout)]

        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in range(0, len(buck) - batch_size + 1, batch_size)])
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        pyrandom.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(buck)
            self.ndlabel.append(label)

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        if self.major_axis == 1:
            data = self.nddata[i][j : j + self.batch_size].T
            label = self.ndlabel[i][j : j + self.batch_size].T
        else:
            data = self.nddata[i][j : j + self.batch_size]
            label = self.ndlabel[i][j : j + self.batch_size]
        return DataBatch(
            [array(data)],
            [array(label)],
            pad=0,
            bucket_key=self.buckets[i],
            provide_data=[DataDesc(self.data_name, data.shape, self.dtype, layout=self.layout)],
            provide_label=[DataDesc(self.label_name, label.shape, self.dtype, layout=self.layout)],
        )
