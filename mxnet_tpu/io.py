"""Data iterators — reference ``python/mxnet/io.py`` (DataIter :182,
NDArrayIter :546, MXDataIter :766, PrefetchingIter :349, ResizeIter) and the
C++ iterator pipeline of ``src/io/`` (batching/shuffle/prefetch layers).

TPU notes: the iterator yields host-side batches; device transfer happens at
op execution (or sharded via parallel.device_put_sharded in the data-parallel
trainer).  Background prefetch uses a thread (the reference's
iter_prefetcher.h), overlapping host pipeline with device compute.
"""
from __future__ import annotations

import collections
import queue as _queue
import threading

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array

__all__ = [
    "DataDesc",
    "DataBatch",
    "DataIter",
    "NDArrayIter",
    "ResizeIter",
    "PrefetchingIter",
    "MXDataIter",
    "CSVIter",
]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape"])):
    """Data layout descriptor (reference io.py DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype, self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch: data list + label list + padding info (reference io.py DataBatch)."""

    def __init__(self, data=None, label=None, pad=None, index=None, bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__,
            [d.shape for d in self.data or []],
            [l.shape for l in self.label or []],
        )


class DataIter:
    """Iterator base (reference io.py:182)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(), pad=self.getpad(), index=self.getindex()
            )
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize to list of (name, numpy) (reference io.py _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = collections.OrderedDict([(default_name, data[0])])
        else:
            data = collections.OrderedDict(
                [("_%d_%s" % (i, default_name), d) for i, d in enumerate(data)]
            )
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    out = collections.OrderedDict()
    for k, v in data.items():
        out[k] = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
    return list(out.items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference io.py:546).

    Supports shuffle, pad/discard/roll_over last-batch handling.
    """

    def __init__(
        self,
        data,
        label=None,
        batch_size=1,
        shuffle=False,
        last_batch_handle="pad",
        data_name="data",
        label_name="softmax_label",
    ):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]
        if last_batch_handle == "discard":
            self.num_data = (self.num_data // batch_size) * batch_size
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size"
        self.cursor = -batch_size
        self.reset()

    @property
    def provide_data(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype) for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype) for k, v in self.label
        ]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and -self.batch_size < self.cursor < self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        assert self.cursor < self.num_data
        if self.cursor + self.batch_size <= self.num_data:
            sel = self.idx[self.cursor : self.cursor + self.batch_size]
            return [array(v[sel]) for _, v in data_source]
        # padding: wrap around
        pad = self.batch_size - (self.num_data - self.cursor)
        sel = np.concatenate([self.idx[self.cursor :self.num_data], self.idx[:pad]])
        return [array(v[sel]) for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (reference io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-backed prefetcher over one or more iterators (reference io.py:349
    and the C++ prefetcher iter_prefetcher.h)."""

    def __init__(self, iters, rename_data=None, rename_label=None, prefetch_depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._queue = _queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self.current_batch = None
        self._start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum(
            [
                [DataDesc(r.get(d.name, d.name), d.shape, d.dtype) for d in i.provide_data]
                for r, i in zip(self.rename_data, self.iters)
            ],
            [],
        )

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum(
            [
                [DataDesc(r.get(d.name, d.name), d.shape, d.dtype) for d in i.provide_label]
                for r, i in zip(self.rename_label, self.iters)
            ],
            [],
        )

    def _worker(self):
        while not self._stop.is_set():
            batches = []
            try:
                for it in self.iters:
                    batches.append(it.next())
            except StopIteration:
                self._queue.put(None)
                return
            merged = DataBatch(
                data=sum([b.data for b in batches], []),
                label=sum([(b.label or []) for b in batches], []),
                pad=batches[0].pad,
                index=batches[0].index,
            )
            while not self._stop.is_set():
                try:
                    self._queue.put(merged, timeout=0.1)
                    break
                except _queue.Full:
                    continue

    def _start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def reset(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
        while not self._queue.empty():
            self._queue.get_nowait()
        for it in self.iters:
            it.reset()
        self._start()

    def iter_next(self):
        batch = self._queue.get()
        if batch is None:
            return False
        self.current_batch = batch
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    def __del__(self):
        self._stop.set()


class CSVIter(NDArrayIter):
    """CSV file iterator (reference src/io/iter_csv.cc, kept host-side)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,), batch_size=1, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape)) if label_shape != (1,) else label
        super().__init__(data, label, batch_size=batch_size, **kwargs)


def MXDataIter(*args, **kwargs):
    raise MXNetError(
        "MXDataIter wrapped C++ iterators in the reference; use ImageRecordIter / "
        "NDArrayIter / gluon DataLoader here (see mxnet_tpu.image / mxnet_tpu.recordio)."
    )
