"""Data iterators — reference ``python/mxnet/io.py`` (DataIter :182,
NDArrayIter :546, MXDataIter :766, PrefetchingIter :349, ResizeIter) and the
C++ iterator pipeline of ``src/io/`` (batching/shuffle/prefetch layers).

TPU notes: the iterator yields host-side batches; device transfer happens at
op execution (or sharded via parallel.device_put_sharded in the data-parallel
trainer).  Background prefetch uses a thread (the reference's
iter_prefetcher.h), overlapping host pipeline with device compute.
"""
from __future__ import annotations

import collections
import queue as _queue
import threading
import warnings

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array

__all__ = [
    "DataDesc",
    "DataBatch",
    "DataIter",
    "NDArrayIter",
    "ResizeIter",
    "PrefetchingIter",
    "MXDataIter",
    "CSVIter", "MNISTIter", "LibSVMIter",
    "ImageRecordIter",
]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape"])):
    """Data layout descriptor (reference io.py DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype, self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch: data list + label list + padding info (reference io.py DataBatch)."""

    def __init__(self, data=None, label=None, pad=None, index=None, bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__,
            [d.shape for d in self.data or []],
            [l.shape for l in self.label or []],
        )


class DataIter:
    """Iterator base (reference io.py:182)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(), pad=self.getpad(), index=self.getindex()
            )
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize to list of (name, numpy) (reference io.py _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = collections.OrderedDict([(default_name, data[0])])
        else:
            data = collections.OrderedDict(
                [("_%d_%s" % (i, default_name), d) for i, d in enumerate(data)]
            )
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    out = collections.OrderedDict()
    for k, v in data.items():
        out[k] = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
    return list(out.items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference io.py:546).

    Supports shuffle, pad/discard/roll_over last-batch handling.
    """

    def __init__(
        self,
        data,
        label=None,
        batch_size=1,
        shuffle=False,
        last_batch_handle="pad",
        data_name="data",
        label_name="softmax_label",
    ):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]
        if last_batch_handle == "discard":
            self.num_data = (self.num_data // batch_size) * batch_size
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size"
        self.cursor = -batch_size
        self.reset()

    @property
    def provide_data(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype) for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype) for k, v in self.label
        ]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and -self.batch_size < self.cursor < self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        assert self.cursor < self.num_data
        if self.cursor + self.batch_size <= self.num_data:
            sel = self.idx[self.cursor : self.cursor + self.batch_size]
            return [array(v[sel]) for _, v in data_source]
        # padding: wrap around
        pad = self.batch_size - (self.num_data - self.cursor)
        sel = np.concatenate([self.idx[self.cursor :self.num_data], self.idx[:pad]])
        return [array(v[sel]) for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (reference io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-backed prefetcher over one or more iterators (reference io.py:349
    and the C++ prefetcher iter_prefetcher.h)."""

    def __init__(self, iters, rename_data=None, rename_label=None, prefetch_depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._queue = _queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self.current_batch = None
        self._start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum(
            [
                [DataDesc(r.get(d.name, d.name), d.shape, d.dtype) for d in i.provide_data]
                for r, i in zip(self.rename_data, self.iters)
            ],
            [],
        )

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum(
            [
                [DataDesc(r.get(d.name, d.name), d.shape, d.dtype) for d in i.provide_label]
                for r, i in zip(self.rename_label, self.iters)
            ],
            [],
        )

    def _worker(self):
        while not self._stop.is_set():
            batches = []
            try:
                for it in self.iters:
                    batches.append(it.next())
            except StopIteration:
                self._queue.put(None)
                return
            merged = DataBatch(
                data=sum([b.data for b in batches], []),
                label=sum([(b.label or []) for b in batches], []),
                pad=batches[0].pad,
                index=batches[0].index,
            )
            while not self._stop.is_set():
                try:
                    self._queue.put(merged, timeout=0.1)
                    break
                except _queue.Full:
                    continue

    def _start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def reset(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
        while not self._queue.empty():
            self._queue.get_nowait()
        for it in self.iters:
            it.reset()
        self._start()

    def iter_next(self):
        batch = self._queue.get()
        if batch is None:
            return False
        self.current_batch = batch
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    def __del__(self):
        self._stop.set()


class CSVIter(NDArrayIter):
    """CSV file iterator (reference src/io/iter_csv.cc, kept host-side)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,), batch_size=1, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape)) if label_shape != (1,) else label
        super().__init__(data, label, batch_size=batch_size, **kwargs)


class MNISTIter(DataIter):
    """MNIST idx-ubyte iterator (reference ``src/io/iter_mnist.cc:80``).

    Reads the classic idx format (images magic 2051, labels magic 2049)
    from ``image``/``label`` paths, normalizes pixels to [0, 1), optionally
    flattens, shuffles with ``seed``, and partitions the stream
    (``num_parts``/``part_index``) exactly like the reference's distributed
    reading (``iter_mnist.cc`` num_parts fields).
    """

    def __init__(self, image, label, batch_size=1, shuffle=False, flat=False,
                 seed=0, silent=True, num_parts=1, part_index=0, **kwargs):
        super().__init__(batch_size)
        import struct

        with open(image, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise MXNetError("%s is not an MNIST image file (magic %d)" % (image, magic))
            img = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
        img = img.reshape(n, rows, cols).astype(np.float32) / 256.0
        with open(label, "rb") as f:
            magic, nl = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise MXNetError("%s is not an MNIST label file (magic %d)" % (label, magic))
            lab = np.frombuffer(f.read(nl), dtype=np.uint8).astype(np.float32)
        if n != nl:
            raise MXNetError("image/label count mismatch: %d vs %d" % (n, nl))
        # partition FIRST to a contiguous file part with proportional floor
        # bounds covering all samples (reference iter_mnist.cc seeks to the
        # part, then shuffles within it). Parts may differ by one sample when
        # num_parts doesn't divide n — as in the reference — so dist_sync
        # loops must fix the per-epoch batch count (examples/.../common/fit.py
        # epoch_size = num_examples/batch/num_workers does exactly this)
        start = (n * part_index) // num_parts
        end = (n * (part_index + 1)) // num_parts
        img, lab = img[start:end], lab[start:end]
        if shuffle:
            order = np.random.RandomState(seed).permutation(len(img))
            img, lab = img[order], lab[order]
        data = img.reshape(len(img), rows * cols) if flat else img[:, None]
        self._inner = NDArrayIter(data, lab, batch_size=batch_size)
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def iter_next(self):
        return self._inner.iter_next()

    def getdata(self):
        return self._inner.getdata()

    def getlabel(self):
        return self._inner.getlabel()

    def getpad(self):
        return self._inner.getpad()

    def next(self):
        return self._inner.next()


class LibSVMIter(DataIter):
    """LibSVM text-format iterator producing CSR batches (reference
    ``src/io/iter_libsvm.cc`` + the sparse prefetcher stack
    ``iter_sparse_prefetcher.h``).

    Each line: ``<label> <idx>:<val> <idx>:<val> ...`` (0-based indices, as
    the reference's ``indexing_mode``\'s default).  ``getdata()`` returns a
    ``CSRNDArray`` slice; labels may themselves be a libsvm file
    (multi-output) or the leading column.
    """

    def __init__(self, data_libsvm, data_shape, batch_size=1,
                 label_libsvm=None, label_shape=None, round_batch=True,
                 **kwargs):
        super().__init__(batch_size)
        self._shape = tuple(data_shape)
        labels, indptr, indices, values = [], [0], [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    indices.append(int(i))
                    values.append(float(v))
                indptr.append(len(indices))
        self._indptr = np.asarray(indptr, np.int64)
        self._indices = np.asarray(indices, np.int64)
        self._values = np.asarray(values, np.float32)
        self._labels = np.asarray(labels, np.float32)
        if label_libsvm is not None:
            # label file is itself libsvm-format sparse rows: idx:val tokens
            # land at their indices in a dense (label_shape,) row (reference
            # iter_libsvm.cc label_shape field)
            raw = []
            width = 0
            with open(label_libsvm) as f:
                for line in f:
                    parts = line.split()
                    if not parts:
                        continue
                    row = []
                    for tok in parts:
                        if ":" in tok:
                            i, v = tok.split(":")
                            row.append((int(i), float(v)))
                        else:
                            row.append((len(row), float(tok)))
                    raw.append(row)
                    width = max(width, 1 + max(i for i, _ in row))
            shape = tuple(label_shape) if label_shape else (width,)
            lab = np.zeros((len(raw),) + shape, np.float32)
            for j, row in enumerate(raw):
                for i, v in row:
                    lab[j, i] = v
            if len(lab) != len(labels):
                raise MXNetError(
                    "label_libsvm has %d rows but data_libsvm has %d"
                    % (len(lab), len(labels)))
            self._labels = lab
        self._n = len(self._labels)
        self._round_batch = round_batch
        self._cursor = 0
        self.provide_data = [DataDesc("data", (batch_size,) + self._shape)]
        self.provide_label = [DataDesc("softmax_label",
                                       (batch_size,) + (np.shape(self._labels)[1:] or ()))]

    def reset(self):
        self._cursor = 0

    def _row_index(self, r):
        # pad rows: round_batch=True wraps to the stream start (reference
        # iter_batchloader.h:103-121); round_batch=False repeats the last
        # real row (reference leaves stale slots — consumers drop pad rows)
        if r < self._n:
            return r
        return r % self._n if self._round_batch else self._n - 1

    def _csr_rows(self, start, stop):
        from .ndarray.sparse import csr_matrix

        rows = []
        for r in range(start, stop):
            r = self._row_index(r)
            rows.append((self._indptr[r], self._indptr[r + 1]))
        indptr = np.zeros(len(rows) + 1, np.int64)
        idx, val = [], []
        for j, (a, b) in enumerate(rows):
            idx.append(self._indices[a:b])
            val.append(self._values[a:b])
            indptr[j + 1] = indptr[j] + (b - a)
        idx = np.concatenate(idx) if idx else np.zeros(0, np.int64)
        val = np.concatenate(val) if val else np.zeros(0, np.float32)
        return csr_matrix((val, idx, indptr),
                          shape=(len(rows),) + self._shape)

    def iter_next(self):
        if self._cursor >= self._n:
            return False
        # reference batch-loader semantics (iter_batchloader.h:102-125): the
        # incomplete tail batch is still returned, padded to batch_size, with
        # getpad() == batch_size - real rows; round_batch only controls
        # whether the NEXT epoch starts mid-stream (we always reset instead)
        self._start = self._cursor
        self._cursor += self.batch_size
        return True

    def getdata(self):
        return [self._csr_rows(self._start, self._start + self.batch_size)]

    def getlabel(self):
        from . import ndarray as _nd

        lab = np.stack([self._labels[self._row_index(r)]
                        for r in range(self._start, self._start + self.batch_size)])
        return [_nd.array(lab)]

    def getpad(self):
        # the tail batch wraps to fill batch_size and REPORTS the wrapped
        # row count as pad (DataBatch.pad contract: consumers drop them)
        return max(0, self._start + self.batch_size - self._n)


def MXDataIter(*args, **kwargs):
    raise MXNetError(
        "MXDataIter wrapped C++ iterators in the reference; use ImageRecordIter / "
        "NDArrayIter / gluon DataLoader here (see mxnet_tpu.image / mxnet_tpu.recordio)."
    )


class ImageRecordIter(DataIter):
    """Image-record iterator over .rec files — reference
    ``src/io/iter_image_recordio_2.cc`` (ImageRecordIter v2: multithreaded
    JPEG decode + augmentation + batching) with the hot path in the native
    C++ loader (this repo's ``src/io/batch_loader.cc``) and a background
    prefetch thread (reference ``src/io/iter_prefetcher.h``).

    Falls back to a pure-Python decode path (recordio + PIL) when the native
    toolchain is unavailable.
    """

    def __init__(
        self,
        path_imgrec,
        data_shape,
        batch_size,
        label_width=1,
        shuffle=False,
        rand_crop=False,
        rand_mirror=False,
        mean_r=0.0,
        mean_g=0.0,
        mean_b=0.0,
        std_r=1.0,
        std_g=1.0,
        std_b=1.0,
        preprocess_threads=4,
        seed=0,
        prefetch_depth=2,
        round_batch=True,
        data_name="data",
        label_name="softmax_label",
        num_parts=1,
        part_index=0,
        pad=0,
        max_random_scale=1.0,
        min_random_scale=1.0,
        **kwargs,
    ):
        super().__init__(batch_size)
        from . import _native

        _IGNORED_DEFAULTS = {
            "max_random_aspect_ratio": 0.0, "max_random_rotate_angle": 0,
            "max_random_shear_ratio": 0.0, "max_img_size": 0.0, "min_img_size": 0.0,
            "max_random_h": 0, "max_random_s": 0, "max_random_l": 0,
            "max_random_contrast": 0.0, "max_random_illumination": 0.0,
            "fill_value": 255, "inter_method": 1, "resize": -1,
        }
        for k, v in kwargs.items():
            if k in _IGNORED_DEFAULTS:
                if v != _IGNORED_DEFAULTS[k]:
                    warnings.warn(
                        "ImageRecordIter: augmentation %s=%r is not implemented "
                        "in this data plane yet; it will be IGNORED" % (k, v))
            else:
                warnings.warn("ImageRecordIter: unknown argument %s=%r ignored" % (k, v))

        self.data_shape = tuple(data_shape)  # (C, H, W)
        assert len(self.data_shape) == 3, "data_shape must be (channels, height, width)"
        self.label_width = label_width
        self.data_name = data_name
        self.label_name = label_name
        # round_batch accepted for API parity but inert: the tail batch is
        # always emitted with getpad() set and undefined pad rows (reference
        # round_batch=0 behavior); the round_batch=1 wrap-from-start fill is
        # not implemented — consumers must drop pad rows either way
        del round_batch
        self._mean = np.array([mean_r, mean_g, mean_b], dtype=np.float32)
        self._std = np.array([std_r, std_g, std_b], dtype=np.float32)
        self._lib = _native.lib()
        # multi-worker sharding (reference kNumPart/kPartIndex in
        # iter_image_recordio_2.cc): worker i keeps every num_parts-th record,
        # truncated so every part has the SAME length (unequal parts deadlock
        # dist_sync collectives at the epoch tail). The native loader does not
        # partition / pixel-pad / scale-augment yet; those modes use the
        # python record path.
        self._num_parts = int(num_parts)
        self._part_index = int(part_index)
        if not 0 <= self._part_index < self._num_parts:
            raise ValueError("part_index %d out of range for num_parts %d"
                             % (self._part_index, self._num_parts))
        self._pad_px = int(pad)
        self._max_scale = float(max_random_scale)
        self._min_scale = float(min_random_scale)
        if self._num_parts > 1 or self._pad_px > 0 or self._max_scale != 1.0 or self._min_scale != 1.0:
            self._lib = None
        self._handle = None
        c, h, w = self.data_shape
        if self._lib is not None:
            import ctypes as _ct

            self._handle = self._lib.MXTImageRecordLoaderCreate(
                path_imgrec.encode(),
                batch_size,
                h,
                w,
                c,
                label_width,
                int(rand_crop),
                int(rand_mirror),
                int(shuffle),
                int(preprocess_threads),
                int(seed),
                self._mean.ctypes.data_as(_ct.POINTER(_ct.c_float)),
                self._std.ctypes.data_as(_ct.POINTER(_ct.c_float)),
            )
            if not self._handle:
                raise MXNetError("cannot open record file %s" % path_imgrec)
            self._num = int(self._lib.MXTImageRecordLoaderSize(self._handle))
        else:
            from .recordio import MXRecordIO, unpack_img

            self._records = []
            rec = MXRecordIO(path_imgrec, "r")
            i = 0
            while True:
                item = rec.read()
                if item is None:
                    break
                # filter while reading: residency stays at ~1/num_parts
                if i % self._num_parts == self._part_index:
                    self._records.append(item)
                i += 1
            rec.close()
            self._unpack_img = unpack_img
            if self._num_parts > 1:
                equal = i // self._num_parts  # same length on every worker
                self._records = self._records[:equal]
            self._num = len(self._records)
            self._order = np.arange(self._num)
            self._shuffle = shuffle
            self._rand_mirror = rand_mirror
            self._rand_crop = rand_crop
            self._rng = np.random.RandomState(seed)
            if self._shuffle:  # shuffle epoch 1 too (native Reset() does)
                self._rng.shuffle(self._order)
            self._cursor = 0
        if self._num == 0:
            raise MXNetError("record file %s is empty" % path_imgrec)
        self._queue = _queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self._current = None
        self._start()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape, np.float32)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape, np.float32)]

    def __len__(self):
        return self._num

    def _produce(self):
        """Produces (data, label, valid) or None at epoch end."""
        c, h, w = self.data_shape
        if self._handle is not None:
            import ctypes as _ct

            data = np.zeros((self.batch_size, c, h, w), dtype=np.float32)
            label = np.zeros((self.batch_size, self.label_width), dtype=np.float32)
            valid = self._lib.MXTImageRecordLoaderNext(
                self._handle,
                data.ctypes.data_as(_ct.POINTER(_ct.c_float)),
                label.ctypes.data_as(_ct.POINTER(_ct.c_float)),
            )
            if valid <= 0:
                return None
            return data, label, int(valid)
        # pure-Python fallback
        if self._cursor >= self._num:
            return None
        valid = min(self.batch_size, self._num - self._cursor)
        data = np.zeros((self.batch_size, c, h, w), dtype=np.float32)
        label = np.zeros((self.batch_size, self.label_width), dtype=np.float32)
        for i in range(valid):
            header, img = self._unpack_img(self._records[self._order[self._cursor + i]])
            if img.ndim == 2:
                img = np.stack([img] * c, axis=-1)
            if self._max_scale != 1.0 or self._min_scale != 1.0:
                # random isotropic rescale before cropping (reference
                # image_aug_default.cc max/min_random_scale)
                from PIL import Image

                sc = self._rng.uniform(self._min_scale, self._max_scale)
                nh = max(h, int(round(img.shape[0] * sc)))
                nw = max(w, int(round(img.shape[1] * sc)))
                img = np.asarray(Image.fromarray(img).resize((nw, nh)))
            if self._pad_px > 0:
                pp = self._pad_px
                img = np.pad(img, ((pp, pp), (pp, pp), (0, 0)), mode="constant")
            if self._rand_crop and img.shape[0] > h and img.shape[1] > w:
                oy = self._rng.randint(0, img.shape[0] - h + 1)
                ox = self._rng.randint(0, img.shape[1] - w + 1)
                img = img[oy : oy + h, ox : ox + w]
            if img.shape[:2] != (h, w):
                from PIL import Image

                img = np.asarray(Image.fromarray(img).resize((w, h)))
            if self._rand_mirror and self._rng.rand() < 0.5:
                img = img[:, ::-1]
            chw = img.astype(np.float32).transpose(2, 0, 1)[:c]
            data[i] = (chw - self._mean[:c, None, None]) / self._std[:c, None, None]
            lab = np.atleast_1d(np.asarray(header.label, dtype=np.float32))
            label[i, : min(self.label_width, lab.size)] = lab[: self.label_width]
        self._cursor += valid
        return data, label, valid

    def _worker(self):
        while not self._stop.is_set():
            try:
                out = self._produce()
            except BaseException as exc:  # propagate to the consumer thread
                out = ("error", exc)
            while not self._stop.is_set():
                try:
                    self._queue.put(out, timeout=0.1)
                    break
                except _queue.Full:
                    continue
            if out is None or (isinstance(out, tuple) and len(out) == 2 and out[0] == "error"):
                return

    def _start(self):
        self._stop.clear()
        self._exhausted = False
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def reset(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
        while not self._queue.empty():
            self._queue.get_nowait()
        if self._handle is not None:
            self._lib.MXTImageRecordLoaderReset(self._handle)
        else:
            if self._shuffle:
                self._rng.shuffle(self._order)
            self._cursor = 0
        self._start()

    def iter_next(self):
        if self._exhausted:
            return False
        out = self._queue.get()
        if out is None:
            self._exhausted = True
            return False
        if isinstance(out, tuple) and len(out) == 2 and out[0] == "error":
            self._exhausted = True
            raise out[1]
        data, label, valid = out
        # both round_batch modes emit the padded tail batch with
        # getpad() == batch_size - valid (reference iter_batchloader.h:102-125;
        # round_batch only changes what fills the pad rows there)
        pad = self.batch_size - valid
        lab = label[:, 0] if self.label_width == 1 else label
        self._current = DataBatch(
            data=[array(data)],
            label=[array(lab)],
            pad=pad,
            provide_data=self.provide_data,
            provide_label=self.provide_label,
        )
        return True

    def next(self):
        if self.iter_next():
            return self._current
        raise StopIteration

    def getdata(self):
        return self._current.data

    def getlabel(self):
        return self._current.label

    def getpad(self):
        return self._current.pad

    def __del__(self):
        try:
            stop = getattr(self, "_stop", None)
            if stop is not None:
                stop.set()
            thread = getattr(self, "_thread", None)
            if thread is not None and thread is not threading.current_thread():
                # drain so a blocked put() wakes, then join before freeing
                # the native handle the worker may still be using
                try:
                    while True:
                        self._queue.get_nowait()
                except _queue.Empty:
                    pass
                thread.join(timeout=5.0)
            if getattr(self, "_handle", None):
                self._lib.MXTImageRecordLoaderFree(self._handle)
                self._handle = None
        except Exception:
            # interpreter shutdown: module globals may already be torn down
            pass
