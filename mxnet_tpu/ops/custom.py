"""Custom-op bridge — reference ``src/operator/custom/custom.cc`` (engine-side
async custom op) + ``python/mxnet/operator.py:426,472,692`` (CustomOp,
CustomOpProp, register).

TPU-native design: a frontend-defined op runs arbitrary host Python (numpy,
cython, ...) inside a traced/jitted graph via ``jax.pure_callback`` — the
escape hatch SURVEY §7.3 calls for (rcnn's proposal_target).  Gradients route
through ``jax.custom_vjp`` whose backward is a second host callback into
``CustomOp.backward``.  Shapes/dtypes come from the prop's ``infer_shape`` /
``infer_type``, exactly the contract the reference's C++ bridge enforces
through MXCustomOpInfo callbacks.
"""
from __future__ import annotations

import numpy as np

from .registry import register

# op_type -> CustomOpProp subclass (reference mx.operator.register registry)
PROP_REGISTRY = {}


def register_prop(op_type, prop_cls):
    PROP_REGISTRY[op_type] = prop_cls


def unregister_prop(op_type):
    """Remove a registration (used by wrappers that register per-instance,
    e.g. torch_bridge.TorchModule, so wrapped modules can be released)."""
    PROP_REGISTRY.pop(op_type, None)
    for key in [k for k in _META_PROP_CACHE if k[0] == op_type]:
        _META_PROP_CACHE.pop(key, None)


_META_PROP_CACHE = {}


def _make_prop(attrs, metadata_only=False):
    """Instantiates the registered CustomOpProp for these attrs.

    ``metadata_only=True`` (Symbol building / output counting) may return a
    cached instance — those queries are pure.  Execution paths always get a
    fresh prop, matching the reference's prop-per-operator-node lifetime so
    stateful props never cross-contaminate between layers/models.
    """
    attrs = dict(attrs)
    attrs.pop("training", None)  # frontend-injected, not a prop kwarg
    op_type = attrs.pop("op_type", None)
    if op_type is None:
        raise ValueError("Custom op requires op_type=")
    if op_type not in PROP_REGISTRY:
        raise ValueError(
            "custom op type %r is not registered (use mx.operator.register)" % op_type
        )
    # reference semantics: every kwarg reaches the prop as a string
    str_attrs = {k: str(v) for k, v in attrs.items()}
    if metadata_only:
        key = (op_type, tuple(sorted(str_attrs.items())))
        prop = _META_PROP_CACHE.get(key)
        if prop is not None and type(prop) is PROP_REGISTRY[op_type]:
            return prop
        prop = PROP_REGISTRY[op_type](**str_attrs)
        _META_PROP_CACHE[key] = prop
        return prop
    return PROP_REGISTRY[op_type](**str_attrs)


def num_outputs_for(attrs):
    return len(_make_prop(attrs, metadata_only=True).list_outputs())


def _req_list(n, req="write"):
    return [req] * n


@register("Custom")
def custom(*data, training=False, **attrs):
    """Runs a registered CustomOp (reference ``mx.nd.Custom``).

    ``op_type`` selects the registered ``CustomOpProp``; remaining attrs are
    forwarded to the prop constructor as strings.  ``training`` is injected
    by the frontends (autograd recording state / executor is_train), becoming
    the ``is_train`` flag of ``CustomOp.forward``.
    """
    import jax

    prop = _make_prop(attrs)
    in_shapes = [tuple(d.shape) for d in data]
    shape_res = prop.infer_shape(in_shapes)
    if len(shape_res) == 3:
        in_shapes, out_shapes, aux_shapes = shape_res
    else:
        in_shapes, out_shapes = shape_res
        aux_shapes = []
    in_types = [np.dtype(d.dtype) for d in data]
    type_res = prop.infer_type(in_types)
    if len(type_res) == 3:
        _, out_types, _ = type_res
    else:
        _, out_types = type_res
    n_out = len(prop.list_outputs())
    out_specs = tuple(
        jax.ShapeDtypeStruct(tuple(s), np.dtype(t))
        for s, t in zip(out_shapes, out_types)
    )
    if aux_shapes:
        raise NotImplementedError(
            "auxiliary states in custom ops are not supported; keep state on "
            "the prop/op instance instead"
        )
    op_holder = {}

    def _get_op():
        if "op" not in op_holder:
            op_holder["op"] = prop.create_operator(None, in_shapes, in_types)
        return op_holder["op"]

    is_train = bool(training)

    def _host_forward(*arrays):
        from ..ndarray.ndarray import array as nd_array

        in_nd = [nd_array(np.asarray(a)) for a in arrays]
        out_nd = [
            nd_array(np.zeros(s, dtype=np.dtype(t)))
            for s, t in zip(out_shapes, out_types)
        ]
        _get_op().forward(
            is_train=is_train,
            req=_req_list(n_out),
            in_data=in_nd,
            out_data=out_nd,
            aux=[],
        )
        return tuple(np.asarray(o.asnumpy(), dtype=np.dtype(t)) for o, t in zip(out_nd, out_types))

    @jax.custom_vjp
    def _fn(*jargs):
        out = jax.pure_callback(_host_forward, out_specs, *jargs, vmap_method="sequential")
        return tuple(out)

    def _fwd(*jargs):
        out = jax.pure_callback(_host_forward, out_specs, *jargs, vmap_method="sequential")
        return tuple(out), (jargs, tuple(out))

    def _bwd(res, cts):
        jargs, outs = res
        in_specs = tuple(
            jax.ShapeDtypeStruct(tuple(s), np.dtype(t))
            for s, t in zip(in_shapes, in_types)
        )

        def _host_backward(*flat):
            from ..ndarray.ndarray import array as nd_array

            n_in = len(in_shapes)
            ins = flat[:n_in]
            o_data = flat[n_in : n_in + n_out]
            o_grad = flat[n_in + n_out :]
            in_nd = [nd_array(np.asarray(a)) for a in ins]
            out_nd = [nd_array(np.asarray(a)) for a in o_data]
            ograd_nd = [nd_array(np.asarray(a)) for a in o_grad]
            igrad_nd = [
                nd_array(np.zeros(s, dtype=np.dtype(t)))
                for s, t in zip(in_shapes, in_types)
            ]
            _get_op().backward(
                req=_req_list(len(in_shapes)),
                out_grad=ograd_nd,
                in_data=in_nd,
                out_data=out_nd,
                in_grad=igrad_nd,
                aux=[],
            )
            return tuple(
                np.asarray(g.asnumpy(), dtype=np.dtype(t))
                for g, t in zip(igrad_nd, in_types)
            )

        igrads = jax.pure_callback(
            _host_backward, in_specs, *(tuple(jargs) + tuple(outs) + tuple(cts)),
            vmap_method="sequential",
        )
        return tuple(igrads)

    _fn.defvjp(_fwd, _bwd)
    out = _fn(*data)
    return out if n_out > 1 else out[0]
