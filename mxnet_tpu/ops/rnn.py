"""Fused recurrent ops — TPU-native equivalent of reference
``src/operator/rnn-inl.h`` / ``rnn_impl.h`` (cuDNN fused RNN).

The whole sequence loop is a single ``lax.scan`` per layer/direction: XLA
compiles it to one while-loop kernel with the gate matmuls on the MXU.
Parameters use the reference's packed-vector convention (all i2h/h2h weights
for every layer+direction concatenated, then all biases) so gluon's fused
layers and checkpoint format match the reference (rnn_layer.py flattening).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, stable_eager

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _layer_param_size(mode, input_size, hidden, directions):
    g = _GATES[mode]
    return directions * g * hidden * (input_size + hidden + 2)


def rnn_param_size(mode, input_size, hidden, num_layers, bidirectional):
    """Total packed parameter count (reference rnn-inl.h GetParamSize)."""
    d = 2 if bidirectional else 1
    size = _layer_param_size(mode, input_size, hidden, d)
    for _ in range(num_layers - 1):
        size += _layer_param_size(mode, d * hidden, hidden, d)
    return size


def _unpack_params(params, mode, input_size, hidden, num_layers, d):
    """Split the packed vector into per-(layer,direction) weight/bias arrays."""
    g = _GATES[mode]
    shapes_w = []
    for layer in range(num_layers):
        isz = input_size if layer == 0 else d * hidden
        for _ in range(d):
            shapes_w.append(((g * hidden, isz), (g * hidden, hidden)))
    ws, pos = [], 0
    for (wi_shape, wh_shape) in shapes_w:
        ni = wi_shape[0] * wi_shape[1]
        wi = params[pos : pos + ni].reshape(wi_shape)
        pos += ni
        nh = wh_shape[0] * wh_shape[1]
        wh = params[pos : pos + nh].reshape(wh_shape)
        pos += nh
        ws.append((wi, wh))
    bs = []
    for _ in range(num_layers * d):
        bi = params[pos : pos + g * hidden]
        pos += g * hidden
        bh = params[pos : pos + g * hidden]
        pos += g * hidden
        bs.append((bi, bh))
    return [w + b for w, b in zip(ws, bs)]


def _step_fn(mode, hidden, clip_min=None, clip_max=None, clip_nan=False):
    if mode == "lstm":

        def step(carry, x_gates, wh, bh):
            h, c = carry
            gates = x_gates + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c = f * c + i * g
            # per-step cell clipping (reference cudnn_rnn-inl.h state clip):
            # must happen inside the scan or long sequences still diverge
            if clip_min is not None and clip_max is not None:
                if clip_nan:
                    c = jnp.nan_to_num(c, nan=0.0)
                c = jnp.clip(c, clip_min, clip_max)
            h = o * jnp.tanh(c)
            return (h, c), h

        return step
    if mode == "gru":

        def step(carry, x_gates, wh, bh):
            (h,) = carry
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(x_gates, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h = (1 - z) * n + z * h
            return (h,), h

        return step

    act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))

    def step(carry, x_gates, wh, bh):
        (h,) = carry
        h = act(x_gates + h @ wh.T + bh)
        return (h,), h

    return step


def _run_layer(x, h0, c0, wi, wh, bi, bh, mode, hidden, reverse,
               clip_min=None, clip_max=None, clip_nan=False):
    """One direction of one layer over the full sequence.  x: (T, N, I)."""
    # hoist the input projection out of the scan: one big MXU matmul (T*N, I)
    t, n, isz = x.shape
    x_gates = (x.reshape(t * n, isz) @ wi.T + bi).reshape(t, n, -1)
    step = _step_fn(mode, hidden, clip_min, clip_max, clip_nan)
    carry = (h0, c0) if mode == "lstm" else (h0,)

    def body(carry, xg):
        return step(carry, xg, wh, bh)

    carry, ys = jax.lax.scan(body, carry, x_gates, reverse=reverse)
    return ys, carry


@register(
    "RNN",
    aux=(),
    inputs_fn=lambda attrs: ["data", "parameters", "state", "state_cell"]
    if attrs.get("mode", "lstm") == "lstm"
    else ["data", "parameters", "state"],
    infer_params=lambda attrs, shapes: _rnn_infer(attrs, shapes),
)
@stable_eager
def rnn(
    data,
    parameters,
    state,
    state_cell=None,
    *,
    state_size,
    num_layers,
    mode="lstm",
    bidirectional=False,
    p=0.0,
    state_outputs=False,
    lstm_state_clip_min=None,
    lstm_state_clip_max=None,
    lstm_state_clip_nan=False,
    training=False,
    key=None,
):
    """Fused multi-layer RNN (reference src/operator/rnn-inl.h).

    data: (T, N, I) — sequence-major like the reference's fused op.
    state: (L*D, N, H); state_cell likewise for LSTM.
    Returns out (T, N, D*H) [+ final h [+ final c for lstm]].
    """
    d = 2 if bidirectional else 1
    hidden = state_size
    layers = _unpack_params(parameters, mode, data.shape[2], hidden, num_layers, d)
    x = data
    h_finals, c_finals = [], []
    for layer in range(num_layers):
        outs = []
        for direction in range(d):
            li = layer * d + direction
            wi, wh, bi, bh = layers[li]
            h0 = state[li]
            c0 = state_cell[li] if mode == "lstm" else None
            ys, carry = _run_layer(
                x, h0, c0, wi, wh, bi, bh, mode, hidden, reverse=direction == 1,
                clip_min=lstm_state_clip_min, clip_max=lstm_state_clip_max,
                clip_nan=lstm_state_clip_nan,
            )
            outs.append(ys)
            h_finals.append(carry[0])
            if mode == "lstm":
                c_finals.append(carry[1])
        x = outs[0] if d == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0 and training and layer < num_layers - 1 and key is not None:
            keep = jax.random.bernoulli(jax.random.fold_in(key, layer), 1 - p, x.shape)
            x = jnp.where(keep, x / (1 - p), 0)
    out_h = jnp.stack(h_finals)
    if mode == "lstm":
        return x, out_h, jnp.stack(c_finals)
    return x, out_h


def _rnn_infer(attrs, shapes):
    dshape = shapes["data"]
    hidden = attrs["state_size"]
    nl = attrs["num_layers"]
    bi = attrs.get("bidirectional", False)
    d = 2 if bi else 1
    mode = attrs.get("mode", "lstm")
    out = {
        "parameters": (rnn_param_size(mode, dshape[2], hidden, nl, bi),),
        "state": (nl * d, dshape[1], hidden),
    }
    if mode == "lstm":
        out["state_cell"] = (nl * d, dshape[1], hidden)
    return out


@register("split_v2")
def split_v2(data, *, indices_or_sections, axis=0, squeeze_axis=False):
    """numpy-style split (reference matrix_op split_v2)."""
    if isinstance(indices_or_sections, int):
        parts = jnp.split(data, indices_or_sections, axis=axis)
    else:
        parts = jnp.split(data, list(indices_or_sections), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(s, axis=axis) for s in parts]
    return tuple(parts)
