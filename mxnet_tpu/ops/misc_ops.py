"""Long-tail operator parity — the remaining reference registrations found by
diffing ``NNVM_REGISTER_OP``/``MXNET_REGISTER_OP_PROPERTY`` sites against this
registry: v1 op aliases, internal helper ops the frontends emit, image
tensor ops (``src/operator/image/image_random.cc``), sparse-flavored ops in
their dense formulation, and IdentityAttachKLSparseReg
(``src/operator/identity_attach_KL_sparse_reg-inl.h``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, alias


# -- v1 / cudnn aliases: the reference kept pre-NNVM copies of conv/pool/BN
# (src/operator/convolution_v1.cc etc.); semantics match the modern ops ------
alias("Convolution", "Convolution_v1")
alias("Pooling", "Pooling_v1")
alias("BatchNorm", "BatchNorm_v1", "CuDNNBatchNorm")
alias("MakeLoss", "make_loss")
# gradient accumulation add (src/operator/tensor/elemwise_binary_op_basic.cc
# _grad_add) and the sparse-capable embedding: dense formulations here
alias("elemwise_add", "_grad_add")
alias("Embedding", "_contrib_SparseEmbedding")


@register("hard_sigmoid")
def hard_sigmoid(data, *, alpha=0.2, beta=0.5):
    """clip(alpha*x + beta, 0, 1) (reference elemwise_unary_op_basic.cc)."""
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("reshape_like")
def reshape_like(lhs, rhs):
    """Reshape lhs to rhs's shape (reference elemwise_unary_op_basic.cc)."""
    return lhs.reshape(rhs.shape)


@register("_copyto", alias=["copyto"])
def _copyto(data):
    """Identity/device copy (reference ndarray_function copy; device
    placement is XLA's job here)."""
    return jnp.asarray(data)


@register("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs):
    """Identity on lhs carrying rhs's storage attrs (reference
    elemwise_unary_op_basic.cc; dense here, so plain identity)."""
    return jnp.asarray(lhs)


@register("_ravel_multi_index", alias=["ravel_multi_index"])
def ravel_multi_index(data, *, shape):
    """(reference src/operator/tensor/ravel.cc) data: (ndim, n) indices."""
    shape = tuple(shape)
    return jnp.ravel_multi_index(
        tuple(data[i].astype(jnp.int32) for i in range(len(shape))), shape,
        mode="clip").astype(data.dtype)


@register("_unravel_index", alias=["unravel_index"])
def unravel_index(data, *, shape):
    """(reference src/operator/tensor/ravel.cc) -> (ndim, n) indices."""
    shape = tuple(shape)
    unr = jnp.unravel_index(data.astype(jnp.int32), shape)
    return jnp.stack(unr).astype(data.dtype)


@register("_square_sum", alias=["square_sum"])
def square_sum(data, *, axis=None, keepdims=False, exclude=False):
    """sum(x^2) fused reduce (reference square_sum-inl.h; the rowsparse
    optimization is moot on dense XLA, which fuses this anyway)."""
    from .reduce import _norm_axis

    ax = _norm_axis(data.ndim, axis, exclude)
    return jnp.sum(data * data, axis=ax, keepdims=keepdims)


@register("_scatter_plus_scalar")
def _scatter_plus_scalar(data, *, scalar=1.0):
    """Sparse-storage-preserving scalar add (reference
    elemwise_binary_scalar_op_basic.cc); dense: plain add."""
    return data + scalar


@register("_scatter_minus_scalar")
def _scatter_minus_scalar(data, *, scalar=1.0):
    return data - scalar


@register("_slice_assign", alias=["slice_assign"])
def _slice_assign(lhs, rhs, *, begin, end, step=()):
    """lhs with lhs[begin:end:step] = rhs (reference matrix_op _slice_assign,
    the engine op behind NDArray.__setitem__)."""
    from .matrix import _slice_index

    return jnp.asarray(lhs).at[_slice_index(lhs.ndim, begin, end, step)].set(rhs)


@register("_slice_assign_scalar", alias=["slice_assign_scalar"])
def _slice_assign_scalar(data, *, begin, end, scalar=0.0, step=()):
    from .matrix import _slice_index

    return jnp.asarray(data).at[_slice_index(data.ndim, begin, end, step)].set(scalar)


@register("_image_to_tensor", alias=["image_to_tensor"])
def image_to_tensor(data):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference
    src/operator/image/image_random.cc:41)."""
    if data.ndim == 3:
        return jnp.transpose(data.astype(jnp.float32) / 255.0, (2, 0, 1))
    return jnp.transpose(data.astype(jnp.float32) / 255.0, (0, 3, 1, 2))


@register("_image_normalize", alias=["image_normalize"])
def image_normalize(data, *, mean=(0.0,), std=(1.0,)):
    """Channel-wise (x - mean) / std on CHW float tensors (reference
    src/operator/image/image_random.cc:51)."""
    mean = jnp.asarray(mean, data.dtype)
    std = jnp.asarray(std, data.dtype)
    if data.ndim == 3:
        return (data - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    return (data - mean.reshape(1, -1, 1, 1)) / std.reshape(1, -1, 1, 1)


@register("_sparse_adagrad_update", mutates=("history",))
def sparse_adagrad_update(weight, grad, history, *, lr, epsilon=1e-7, wd=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0):
    """AdaGrad update (reference optimizer_op.cc:651 _sparse_adagrad_update);
    dense formulation — XLA only touches rows whose gradient is nonzero after
    fusion, the moral equivalent of the rowsparse kernel."""
    from .optimizer_ops import _prep_grad

    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_hist = history + g * g
    return weight - lr * g / (jnp.sqrt(new_hist) + epsilon), new_hist


def _kl_sparse_aux_update(attrs, raw_outputs, aux):
    """Update the moving average of activations (reference
    identity_attach_KL_sparse_reg-inl.h:108). The executor passes the raw fn
    result (a single array for this op) and a possibly-empty aux dict."""
    if "moving_avg" not in aux:
        return {}
    momentum = attrs.get("momentum", 0.9)
    out = raw_outputs[0] if isinstance(raw_outputs, tuple) else raw_outputs
    avg = jnp.mean(out, axis=0)
    return {"moving_avg": momentum * aux["moving_avg"] + (1 - momentum) * avg}


def _kl_infer(attrs, shapes):
    return {"moving_avg": (shapes["data"][1],)}


@register("IdentityAttachKLSparseReg", aux=("moving_avg",),
          inputs_fn=lambda attrs: ["data", "moving_avg"],
          infer_params=_kl_infer, aux_update=_kl_sparse_aux_update)
def identity_attach_kl_sparse_reg(data, moving_avg=None, *, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    """Identity forward; backward adds the KL sparseness penalty gradient
    penalty * (-rho/rho_hat + (1-rho)/(1-rho_hat)) using the moving average
    of activations (reference identity_attach_KL_sparse_reg-inl.h:65-111)."""
    rho = sparseness_target
    rho_hat = moving_avg if moving_avg is not None else jnp.mean(data, axis=0)

    @jax.custom_vjp
    def _f(x, rh):
        return x

    def _fwd(x, rh):
        return x, rh

    def _bwd(rh, g):
        rh = jnp.clip(rh, 1e-6, 1 - 1e-6)  # fresh zero-initialized aux
        reg = penalty * (-rho / rh + (1 - rho) / (1 - rh))
        return (g + jnp.broadcast_to(reg, g.shape), None)

    _f.defvjp(_fwd, _bwd)
    return _f(data, jax.lax.stop_gradient(rho_hat))
