"""Linear-algebra operators — reference ``src/operator/tensor/la_op.{h,cc}``
(LAPACK via c_lapack_api.h in the reference; here jnp/jax.scipy.linalg, which
XLA lowers to MXU matmuls and on-device factorization routines).

All ops operate on the last two axes, batching over leading axes, matching
the reference's la_op batch semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl

from .registry import register


def _t(x, do):
    return jnp.swapaxes(x, -1, -2) if do else x


@register("_linalg_gemm", alias=["linalg_gemm"])
def linalg_gemm(A, B, C, *, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, axis=-2):
    """out = alpha * op(A) @ op(B) + beta * C (reference la_op.cc:36)."""
    if axis != -2:
        A = jnp.moveaxis(A, axis, -2)
        B = jnp.moveaxis(B, axis, -2)
        C = jnp.moveaxis(C, axis, -2)
    out = alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b)) + beta * C
    if axis != -2:
        out = jnp.moveaxis(out, -2, axis)
    return out


@register("_linalg_gemm2", alias=["linalg_gemm2"])
def linalg_gemm2(A, B, *, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    """out = alpha * op(A) @ op(B) (reference la_op.cc:109)."""
    if axis != -2:
        A = jnp.moveaxis(A, axis, -2)
        B = jnp.moveaxis(B, axis, -2)
    out = alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b))
    if axis != -2:
        out = jnp.moveaxis(out, -2, axis)
    return out


@register("_linalg_potrf", alias=["linalg_potrf"])
def linalg_potrf(A):
    """Lower Cholesky factor of a symmetric positive-definite matrix
    (reference la_op.cc:176)."""
    return jnp.linalg.cholesky(A)


@register("_linalg_potri", alias=["linalg_potri"])
def linalg_potri(A):
    """Inverse of B = A @ A^T from its lower Cholesky factor A
    (reference la_op.cc:225)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    inv_a = jsl.solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(inv_a, -1, -2), inv_a)


@register("_linalg_trmm", alias=["linalg_trmm"])
def linalg_trmm(A, B, *, transpose=False, rightside=False, alpha=1.0, lower=True):
    """Triangular matrix multiply: out = alpha*op(A)@B (or B@op(A))
    (reference la_op.cc:280). A is triangular."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    tri = _t(tri, transpose)
    return alpha * (jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B))


@register("_linalg_trsm", alias=["linalg_trsm"])
def linalg_trsm(A, B, *, transpose=False, rightside=False, alpha=1.0, lower=True):
    """Solve op(A) @ X = alpha*B (or X @ op(A) = alpha*B) with triangular A
    (reference la_op.cc:343)."""
    if rightside:
        # X @ op(A) = alpha*B  <=>  op(A)^T @ X^T = alpha*B^T
        xt = jsl.solve_triangular(
            A, jnp.swapaxes(alpha * B, -1, -2), lower=lower,
            trans=0 if transpose else 1,
        )
        return jnp.swapaxes(xt, -1, -2)
    return jsl.solve_triangular(A, alpha * B, lower=lower, trans=1 if transpose else 0)


@register("_linalg_sumlogdiag", alias=["linalg_sumlogdiag"])
def linalg_sumlogdiag(A):
    """Sum of log of diagonal entries (reference la_op.cc:406)."""
    d = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@register("_linalg_syrk", alias=["linalg_syrk"])
def linalg_syrk(A, *, transpose=False, alpha=1.0):
    """out = alpha * A @ A^T (or A^T @ A if transpose) (reference la_op.cc:449)."""
    At = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(At, A) if transpose else jnp.matmul(A, At))


@register("_linalg_gelqf", alias=["linalg_gelqf"])
def linalg_gelqf(A):
    """LQ factorization A = L @ Q with Q orthonormal rows (reference
    la_op.cc:506). Via QR of A^T: A^T = Q' R  =>  A = R^T Q'^T."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("_linalg_syevd", alias=["linalg_syevd"])
def linalg_syevd(A):
    """Symmetric eigendecomposition A = U^T diag(L) U; rows of U are the
    eigenvectors (reference la_op.cc:577)."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w
