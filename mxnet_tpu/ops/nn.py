"""Neural-network operators.

TPU-native equivalents of reference ``src/operator/nn/`` (Convolution via
im2col+cuDNN → here ``lax.conv_general_dilated`` straight onto the MXU;
Pooling → ``lax.reduce_window``; BatchNorm/LayerNorm as fused jnp; Softmax
family; Dropout with explicit PRNG key threading; RNN as ``lax.scan``).

Layout: MXNet default NCHW is kept at the API level; XLA:TPU re-lays-out
internally, so no NHWC shim is needed for correctness.  All ops are pure and
jit-traceable; gradients come from jax AD (replacing the hand-written
backward kernels of the reference).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


def _tup(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    return v


# ---------------------------------------------------------------------------
# dense / conv / deconv
# ---------------------------------------------------------------------------


def _fc_inputs(attrs):
    return ["data", "weight"] if attrs.get("no_bias") else ["data", "weight", "bias"]


def _fc_params(attrs, shapes):
    d = shapes["data"]
    nh = attrs["num_hidden"]
    in_dim = int(np.prod(d[1:])) if attrs.get("flatten", True) else d[-1]
    return {"weight": (nh, in_dim), "bias": (nh,)}


@register("FullyConnected", inputs_fn=_fc_inputs, infer_params=_fc_params)
def fully_connected(data, weight, bias=None, *, num_hidden, no_bias=False,
                    flatten=True, accum_dtype=None, out_dtype=None):
    """Dense layer (reference src/operator/nn/fully_connected.cc).

    weight: (num_hidden, in_dim) — MXNet convention.  data flattened to 2D if
    ``flatten`` else applied to the last axis.  One MXU matmul.

    ``accum_dtype``/``out_dtype`` are the precision-tier hooks (ISSUE 15,
    graph_passes/precision.py): the bf16 cast pass sets
    ``accum_dtype="float32"`` so low-precision operands still contract into
    an fp32 accumulator (``preferred_element_type``), and
    ``out_dtype="bfloat16"`` re-narrows the result at the op exit.  Unset
    (every non-tier plan) the lowering is byte-identical to before.
    """
    if flatten:
        x = data.reshape(data.shape[0], -1)
    else:
        x = data
    if accum_dtype is not None:
        out = jax.lax.dot_general(
            x, weight, (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.dtype(accum_dtype))
    else:
        out = jnp.matmul(x, weight.T)
    if not no_bias and bias is not None:
        out = out + bias
    if out_dtype is not None:
        # the precision tier's explicit exit narrowing (the cast IS the
        # point of the pass that sets this attr)
        out = out.astype(out_dtype)  # mxlint: ignore[implicit-downcast]
    return out


def _conv_dims(kernel_ndim, layout=None):
    spatial = "DHW"[-kernel_ndim:]
    if layout is None:
        layout = "NC" + spatial
    if layout not in ("NC" + spatial, "N" + spatial + "C"):
        raise ValueError(f"bad conv layout {layout!r} for {kernel_ndim}-d kernel")
    if layout[1] == "C":  # channel-first: weight (O, I/g, *k)
        rhs = "OI" + layout[2:]
    else:  # channel-last (NHWC et al): weight (O, *k, I/g) — TPU-friendly
        rhs = "O" + layout[1:-1] + "I"
    return (layout, rhs, layout)


def _conv_params(attrs, shapes):
    d = shapes["data"]
    k = attrs["kernel"]
    k = (k,) if isinstance(k, int) else tuple(k)
    g = attrs.get("num_group", 1)
    nf = attrs["num_filter"]
    layout = attrs.get("layout")
    if layout and layout[1] != "C":  # channel-last
        return {"weight": (nf,) + k + (d[-1] // g,), "bias": (nf,)}
    return {"weight": (nf, d[1] // g) + k, "bias": (nf,)}


@register("Convolution", inputs_fn=_fc_inputs, infer_params=_conv_params)
def convolution(
    data,
    weight,
    bias=None,
    *,
    kernel,
    num_filter,
    stride=None,
    dilate=None,
    pad=None,
    num_group=1,
    no_bias=False,
    cudnn_tune=None,
    cudnn_off=False,
    workspace=1024,
    layout=None,
    accum_dtype=None,
    out_dtype=None,
):
    """N-D convolution (reference src/operator/nn/convolution.cc, im2col.h).

    Maps directly to ``lax.conv_general_dilated`` → XLA conv → MXU.  The
    reference's im2col/cuDNN machinery has no TPU analog: XLA tiles the conv
    onto the systolic array itself.

    ``accum_dtype``/``out_dtype``: precision-tier hooks (ISSUE 15) — see
    ``fully_connected``.  ``accum_dtype`` forces the contraction's
    ``preferred_element_type`` (eval twins only: an explicit accumulator
    dtype breaks the conv transpose rule under AD — see the fp32 note
    below); ``out_dtype`` re-narrows at the op exit.  Unset keeps the
    lowering byte-identical.
    """
    kernel = _tup(kernel, len(kernel) if hasattr(kernel, "__len__") else 2)
    n = len(kernel)
    stride = _tup(stride, n)
    dilate = _tup(dilate, n)
    pad = _tup(pad, n) if pad is not None else (0,) * n
    pet = None if accum_dtype is None else jnp.dtype(accum_dtype)
    if (n == 2 and layout in (None, "NCHW")
            and os.environ.get("MXNET_CONV_INTERNAL_LAYOUT") == "NHWC"):
        # experiment knob: run the conv channels-last internally (NCHW kept
        # at the API); XLA's layout assignment usually elides the wrapper
        # transposes — measured in docs/PERF_NOTES.md
        xt = jnp.transpose(data, (0, 2, 3, 1))
        wt = jnp.transpose(weight, (0, 2, 3, 1))
        dnt = jax.lax.conv_dimension_numbers(
            xt.shape, wt.shape, ("NHWC", "OHWI", "NHWC"))
        out = jax.lax.conv_general_dilated(
            xt, wt, window_strides=stride, padding=[(p, p) for p in pad],
            rhs_dilation=dilate, dimension_numbers=dnt,
            feature_group_count=num_group, preferred_element_type=pet)
        out = jnp.transpose(out, (0, 3, 1, 2))
        if not no_bias and bias is not None:
            out = out + bias.reshape(1, -1, 1, 1)
        if out_dtype is not None:
            out = out.astype(out_dtype)  # mxlint: ignore[implicit-downcast]
        return out
    dn = jax.lax.conv_dimension_numbers(data.shape, weight.shape, _conv_dims(n, layout))
    out = jax.lax.conv_general_dilated(
        data,
        weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        # default preferred_element_type=None: the MXU accumulates in f32
        # regardless and bf16 output storage is the mixed-precision
        # contract; forcing an f32 output also breaks the conv transpose
        # rule under AD (cotangent dtype mismatch) — only the eval-plan
        # precision tier (ISSUE 15) sets accum_dtype
        preferred_element_type=pet,
    )
    if not no_bias and bias is not None:
        c_axis = (layout or "NC").index("C")
        bshape = [1] * out.ndim
        bshape[c_axis] = -1
        out = out + bias.reshape(bshape)
    if out_dtype is not None:
        # precision-tier exit narrowing (ISSUE 15): the cast is the point
        out = out.astype(out_dtype)  # mxlint: ignore[implicit-downcast]
    return out


def _deconv_params(attrs, shapes):
    d = shapes["data"]
    k = tuple(attrs["kernel"])
    g = attrs.get("num_group", 1)
    nf = attrs["num_filter"]
    return {"weight": (d[1], nf // g) + k, "bias": (nf,)}


def _deconv_inputs(attrs):
    # deconvolution's no_bias DEFAULTS TO TRUE (reference deconvolution-inl.h)
    return ["data", "weight"] if attrs.get("no_bias", True) else ["data", "weight", "bias"]


@register("Deconvolution", inputs_fn=_deconv_inputs, infer_params=_deconv_params)
def deconvolution(
    data,
    weight,
    bias=None,
    *,
    kernel,
    num_filter,
    stride=None,
    dilate=None,
    pad=None,
    adj=None,
    target_shape=None,
    num_group=1,
    no_bias=True,
    cudnn_tune=None,
    cudnn_off=False,
    workspace=512,
    layout=None,
):
    """Transposed convolution (reference src/operator/nn/deconvolution.cc).

    Implemented as conv_general_dilated with lhs_dilation (the XLA-native
    formulation of a gradient/transposed conv).
    """
    kernel = tuple(kernel)
    n = len(kernel)
    if layout is not None and layout[1] != "C":
        raise NotImplementedError("Deconvolution supports channel-first layouts only")
    stride = _tup(stride, n)
    dilate = _tup(dilate, n)
    pad = _tup(pad, n) if pad is not None else (0,) * n
    adj = _tup(adj, n) if adj is not None else (0,) * n
    # weight layout (in_ch, out_ch/g, *kernel) — MXNet deconv convention.
    # Transposed conv = conv with lhs dilation, flipped kernel, IO swapped.
    w = jnp.flip(weight, axis=tuple(range(2, 2 + n)))
    w = jnp.swapaxes(w, 0, 1) if num_group == 1 else w.reshape(
        (num_group, weight.shape[0] // num_group) + weight.shape[1:]
    ).swapaxes(1, 2).reshape(
        (weight.shape[1] * num_group, weight.shape[0] // num_group) + kernel
    )
    eff_k = tuple((k - 1) * d + 1 for k, d in zip(kernel, dilate))
    padding = [
        (ek - 1 - p, ek - 1 - p + a) for ek, p, a in zip(eff_k, pad, adj)
    ]
    dn = jax.lax.conv_dimension_numbers(data.shape, w.shape, _conv_dims(n))
    out = jax.lax.conv_general_dilated(
        data,
        w,
        window_strides=(1,) * n,
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


@register("Pooling")
def pooling(
    data,
    *,
    kernel=(1, 1),
    pool_type="max",
    global_pool=False,
    stride=None,
    pad=None,
    pooling_convention="valid",
    count_include_pad=True,
    cudnn_off=False,
    p_value=2,
    layout=None,
):
    """Max/avg/sum/lp pooling (reference src/operator/nn/pooling.cc, pool.h).

    ``lax.reduce_window`` lowers to the TPU vector unit.  'full' convention
    (ceil division, reference pool.h) is realized with extra right-padding.
    """
    n = data.ndim - 2
    channel_last = layout is not None and len(layout) > 1 and layout[1] != "C"
    if (n == 2 and layout in (None, "NCHW") and not global_pool
            and os.environ.get("MXNET_POOL_INTERNAL_LAYOUT") == "NHWC"):
        # internal-layout knob like Convolution's — measured NEUTRAL-to-
        # slightly-negative on ResNet-50 (docs/PERF_NOTES.md), so it keys
        # off its own env var and stays off by default
        out = pooling(
            jnp.transpose(data, (0, 2, 3, 1)), kernel=kernel,
            pool_type=pool_type, stride=stride, pad=pad,
            pooling_convention=pooling_convention,
            count_include_pad=count_include_pad, p_value=p_value,
            layout="NHWC")
        return jnp.transpose(out, (0, 3, 1, 2))
    if global_pool:
        ax = tuple(range(1, 1 + n)) if channel_last else tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=ax, keepdims=True)
        if pool_type == "avg":
            return jnp.mean(data, axis=ax, keepdims=True).astype(data.dtype)
        if pool_type == "lp":
            p_ = float(p_value)
            s = jnp.sum(jnp.abs(data.astype(jnp.float32)) ** p_, axis=ax, keepdims=True)
            return (s ** (1.0 / p_)).astype(data.dtype)
        return jnp.sum(data, axis=ax, keepdims=True)
    kernel = _tup(kernel, n)
    stride = _tup(stride, n)
    pad = _tup(pad, n) if pad is not None else (0,) * n
    pads = []
    for i, (k, s, p) in enumerate(zip(kernel, stride, pad)):
        lo = p
        hi = p
        if pooling_convention == "full":
            x = data.shape[(1 if channel_last else 2) + i]
            out_sz = int(np.ceil((x + 2 * p - k) / s)) + 1
            needed = (out_sz - 1) * s + k - (x + 2 * p)
            hi = p + max(needed, 0)
        pads.append((lo, hi))
    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        padding = [(0, 0)] + pads + [(0, 0)]
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        padding = [(0, 0), (0, 0)] + pads
    if pool_type == "max":
        # init must be a concrete scalar of the operand dtype: a traced jnp
        # constant breaks reduce_window's autodiff rule
        if jnp.issubdtype(data.dtype, jnp.floating):
            init = np.asarray(-np.inf, data.dtype)[()]
        else:
            init = np.asarray(np.iinfo(np.dtype(data.dtype)).min, data.dtype)[()]
        return jax.lax.reduce_window(data, init, jax.lax.max, window, strides, padding)
    if pool_type == "sum":
        return jax.lax.reduce_window(data, np.asarray(0, data.dtype)[()], jax.lax.add, window, strides, padding)
    if pool_type == "avg":
        summed = jax.lax.reduce_window(
            data.astype(jnp.float32), 0.0, jax.lax.add, window, strides, padding
        )
        if count_include_pad:
            denom = float(np.prod(kernel))
            out = summed / denom
        else:
            ones = jnp.ones(data.shape, dtype=jnp.float32)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, padding)
            out = summed / counts
        return out.astype(data.dtype)
    if pool_type == "lp":
        p_ = float(p_value)
        summed = jax.lax.reduce_window(
            jnp.abs(data.astype(jnp.float32)) ** p_, 0.0, jax.lax.add, window, strides, padding
        )
        return (summed ** (1.0 / p_)).astype(data.dtype)
    raise ValueError("unknown pool_type %r" % pool_type)


@register("_contrib_AdaptiveAvgPooling2D")
def adaptive_avg_pooling(data, *, output_size=(1, 1)):
    """Adaptive average pool (reference src/operator/contrib/adaptive_avg_pooling.cc)."""
    oh, ow = _tup(output_size, 2)
    n, c, h, w = data.shape
    if h % oh == 0 and w % ow == 0:
        x = data.reshape(n, c, oh, h // oh, ow, w // ow)
        return jnp.mean(x, axis=(3, 5))
    # general case: interpolation-style bin averaging
    hs = jnp.floor(jnp.arange(oh) * h / oh).astype(jnp.int32)
    he = jnp.ceil((jnp.arange(oh) + 1) * h / oh).astype(jnp.int32)
    ws = jnp.floor(jnp.arange(ow) * w / ow).astype(jnp.int32)
    we = jnp.ceil((jnp.arange(ow) + 1) * w / ow).astype(jnp.int32)
    rows = []
    for i in range(oh):
        cols = []
        for j in range(ow):
            cols.append(
                jnp.mean(
                    jax.lax.dynamic_slice(
                        data,
                        (0, 0, int(hs[i]), int(ws[j])),
                        (n, c, int(he[i] - hs[i]), int(we[j] - ws[j])),
                    ),
                    axis=(2, 3),
                )
            )
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


def _bn_params(attrs, shapes):
    c = shapes["data"][attrs.get("axis", 1) % len(shapes["data"])]
    return {"gamma": (c,), "beta": (c,), "moving_mean": (c,), "moving_var": (c,)}


def _bn_aux_update(attrs, outputs, aux_vals):
    """moving = m*moving + (1-m)*batch, the reference's in-place stat update."""
    if attrs.get("use_global_stats"):
        return aux_vals
    _, mean, var = outputs
    m = attrs.get("momentum", 0.9)
    out = dict(aux_vals)
    if "moving_mean" in out:
        out["moving_mean"] = m * out["moving_mean"] + (1 - m) * mean
    if "moving_var" in out:
        out["moving_var"] = m * out["moving_var"] + (1 - m) * var
    return out


@register(
    "BatchNorm",
    aux=("moving_mean", "moving_var"),
    infer_params=_bn_params,
    aux_update=_bn_aux_update,
)
def batch_norm(
    data,
    gamma,
    beta,
    moving_mean,
    moving_var,
    *,
    eps=1e-3,
    momentum=0.9,
    fix_gamma=True,
    use_global_stats=False,
    output_mean_var=False,
    axis=1,
    cudnn_off=False,
    training=False,
):
    """Batch normalization (reference src/operator/nn/batch_norm.cc).

    Functional: returns (out, batch_mean, batch_var); the caller (gluon block /
    executor) folds the running-stat update, since jax arrays are immutable —
    this replaces the reference's in-place aux-state mutation.
    """
    ax = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    if use_global_stats or not training:
        mean, var = moving_mean, moving_var
    else:
        # one-pass stats: both reductions are sibling outputs of ONE fused
        # read of the activation (jnp.var's two-pass form reads it twice —
        # ResNet training is HBM-bound and BN touches every activation,
        # docs/PERF_NOTES.md roofline).  SHIFTED form: squaring (x − m₀)
        # with the running mean as the per-channel reference keeps the
        # E[d²]−E[d]² cancellation proportional to |batch mean − running
        # mean| (small once stats track) instead of |mean|/std — the raw
        # form catastrophically cancels for large-mean channels.
        x32 = data.astype(jnp.float32)
        m0 = moving_mean.astype(jnp.float32).reshape(bshape)
        d = x32 - m0
        dmean = jnp.mean(d, axis=red)
        dex2 = jnp.mean(d * d, axis=red)
        var = jnp.maximum(dex2 - dmean * dmean, 0.0)
        mean = dmean + moving_mean.astype(jnp.float32)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    scale = (g / jnp.sqrt(var + eps)).astype(data.dtype).reshape(bshape)
    shift = (beta - mean * g / jnp.sqrt(var + eps)).astype(data.dtype).reshape(bshape)
    out = data * scale + shift
    return out, mean, var


def _ln_params(attrs, shapes):
    c = shapes["data"][attrs.get("axis", -1) % len(shapes["data"])]
    return {"gamma": (c,), "beta": (c,)}


@register("LayerNorm", infer_params=_ln_params)
def layer_norm(data, gamma, beta, *, axis=-1, eps=1e-5, output_mean_var=False):
    """Layer normalization (reference src/operator/nn/layer_norm.cc)."""
    ax = axis % data.ndim
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=ax, keepdims=True)
    var = jnp.var(x32, axis=ax, keepdims=True)
    norm = ((x32 - mean) / jnp.sqrt(var + eps)).astype(data.dtype)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    out = norm * gamma.reshape(bshape) + beta.reshape(bshape)
    if output_mean_var:
        return out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax)
    return out


@register("InstanceNorm", infer_params=lambda attrs, shapes: {"gamma": (shapes["data"][1],), "beta": (shapes["data"][1],)})
def instance_norm(data, gamma, beta, *, eps=1e-3):
    """Instance norm (reference src/operator/instance_norm.cc)."""
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    norm = (data - mean) / jnp.sqrt(var + eps)
    bshape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    return norm * gamma.reshape(bshape) + beta.reshape(bshape)


@register("LRN")
def lrn(data, *, nsize, alpha=1e-4, beta=0.75, knorm=2.0):
    """Local response norm across channels (reference src/operator/nn/lrn.cc)."""
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    windows = jax.lax.reduce_window(
        padded, 0.0, jax.lax.add, (1, nsize, 1, 1), (1, 1, 1, 1), "valid"
    )
    return data / jnp.power(knorm + alpha * windows / nsize, beta)


# ---------------------------------------------------------------------------
# activations / softmax family
# ---------------------------------------------------------------------------


@register("Activation")
def activation(data, *, act_type):
    """Activation dispatch (reference src/operator/nn/activation.cc)."""
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise ValueError("unknown act_type %r" % act_type)


@register(
    "LeakyReLU",
    inputs_fn=lambda attrs: ["data", "gamma"] if attrs.get("act_type") == "prelu" else ["data"],
    infer_params=lambda attrs, shapes: {"gamma": (shapes["data"][1],)},
)
def leaky_relu(data, gamma=None, *, act_type="leaky", slope=0.25, lower_bound=0.125, upper_bound=0.334, key=None):
    """Leaky/PReLU/ELU/SELU/GELU/RReLU (reference src/operator/leaky_relu.cc)."""
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim and g.ndim == 1:
            g = g.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * (jnp.exp(data) - 1.0))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * (jnp.exp(data) - 1.0))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        if key is None:
            mid = (lower_bound + upper_bound) / 2.0
            return jnp.where(data >= 0, data, mid * data)
        r = jax.random.uniform(key, data.shape, minval=lower_bound, maxval=upper_bound, dtype=data.dtype)
        return jnp.where(data >= 0, data, r * data)
    raise ValueError("unknown act_type %r" % act_type)


@register("softmax")
def softmax(data, *, axis=-1, temperature=None, length=None):
    """Softmax (reference src/operator/nn/softmax.cc)."""
    x = data if temperature in (None, 1.0) else data / temperature
    if length is not None:
        mask = jnp.arange(data.shape[axis]) < jnp.expand_dims(length, -1)
        x = jnp.where(mask, x, -jnp.inf)
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def log_softmax(data, *, axis=-1, temperature=None):
    x = data if temperature in (None, 1.0) else data / temperature
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def softmin(data, *, axis=-1, temperature=None):
    return softmax.op.fn(-data, axis=axis, temperature=temperature)


@register("SoftmaxActivation")
def softmax_activation(data, *, mode="instance"):
    """Deprecated softmax activation (reference softmax_activation.cc)."""
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


def _softmax_output_label_shape(attrs, shapes):
    d = shapes["data"]
    if attrs.get("multi_output"):
        return {"label": (d[0],) + tuple(d[2:])}
    return {"label": tuple(d[:-1])}


@register("SoftmaxOutput", alias=["Softmax"], infer_params=_softmax_output_label_shape)
def softmax_output(
    data,
    label,
    *,
    grad_scale=1.0,
    ignore_label=-1.0,
    multi_output=False,
    use_ignore=False,
    preserve_shape=False,
    normalization="null",
    out_grad=False,
    smooth_alpha=0.0,
):
    """Softmax with implicit CE gradient (reference src/operator/softmax_output.cc).

    Forward returns softmax(data).  The custom VJP reproduces MXNet's fused
    (p - onehot(label)) * grad_scale backward, including ignore_label masking —
    the property rcnn/classification training relies on.
    """
    return _softmax_output_vjp(
        data, label, grad_scale, ignore_label, multi_output, use_ignore, normalization, smooth_alpha
    )


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _softmax_output_vjp(data, label, grad_scale, ignore_label, multi_output, use_ignore, normalization, smooth_alpha):
    return _softmax_output_fwd_only(data, multi_output)


def _softmax_output_fwd_only(data, multi_output):
    if multi_output:
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data, axis=-1)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, multi_output, use_ignore, normalization, smooth_alpha):
    out = _softmax_output_fwd_only(data, multi_output)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, ignore_label, multi_output, use_ignore, normalization, smooth_alpha, res, g):
    out, label = res
    cls_axis = 1 if multi_output else out.ndim - 1
    n_cls = out.shape[cls_axis]
    lbl = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lbl, n_cls, dtype=out.dtype, axis=cls_axis)
    if smooth_alpha:
        onehot = onehot * (1.0 - smooth_alpha) + smooth_alpha / (n_cls - 1) * (1.0 - onehot)
    grad = out - onehot
    if use_ignore:
        keep = (label != ignore_label).astype(out.dtype)
        grad = grad * jnp.expand_dims(keep, cls_axis)
    scale = grad_scale
    if normalization == "valid" and use_ignore:
        valid = jnp.maximum(jnp.sum(label != ignore_label), 1).astype(out.dtype)
        scale = grad_scale / valid
    elif normalization == "batch":
        scale = grad_scale / out.shape[0]
    return (grad * scale, jnp.zeros_like(label))


_softmax_output_vjp.defvjp(_softmax_output_fwd, _softmax_output_bwd)


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------


@register("Dropout")
def dropout(data, *, p=0.5, mode="training", axes=(), training=False, key=None):
    """Dropout (reference src/operator/nn/dropout.cc).

    Deterministic given ``key``; the nd frontend threads a fresh key from the
    global RNG per call (replacing the reference's per-kernel Random resource).
    """
    if not training and mode != "always" or p == 0.0 or key is None:
        return data
    shape = list(data.shape)
    for ax in axes or ():
        shape[ax] = 1
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
    return jnp.where(keep, data / (1.0 - p), jnp.zeros_like(data))


# ---------------------------------------------------------------------------
# losses / outputs
# ---------------------------------------------------------------------------


def _same_as_data(attrs, shapes):
    return {"label": tuple(shapes["data"])}


@register("LinearRegressionOutput", infer_params=_same_as_data)
def linear_regression_output(data, label, *, grad_scale=1.0):
    """Identity fwd, (pred-label)/batch grad (reference src/operator/regression_output.cc)."""
    return _regression_vjp(data, label, grad_scale, "linear")


@register("MAERegressionOutput", infer_params=_same_as_data)
def mae_regression_output(data, label, *, grad_scale=1.0):
    return _regression_vjp(data, label, grad_scale, "mae")


@register("LogisticRegressionOutput", infer_params=_same_as_data)
def logistic_regression_output(data, label, *, grad_scale=1.0):
    return _regression_vjp(data, label, grad_scale, "logistic")


@_partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _regression_vjp(data, label, grad_scale, kind):
    return jax.nn.sigmoid(data) if kind == "logistic" else data


def _regression_fwd(data, label, grad_scale, kind):
    out = jax.nn.sigmoid(data) if kind == "logistic" else data
    return out, (out, label)


def _regression_bwd(grad_scale, kind, res, g):
    out, label = res
    lbl = label.reshape(out.shape)
    if kind == "mae":
        grad = jnp.sign(out - lbl)
    else:
        grad = out - lbl
    return (grad * grad_scale, jnp.zeros_like(label))


_regression_vjp.defvjp(_regression_fwd, _regression_bwd)


@register("MakeLoss")
def make_loss(data, *, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """Turn a tensor into a loss head (reference src/operator/make_loss.cc)."""
    return _make_loss_vjp(data, grad_scale, normalization)


@_partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _make_loss_vjp(data, grad_scale, normalization):
    return data


def _make_loss_fwd(data, grad_scale, normalization):
    return data, (data.shape, data.dtype)


def _make_loss_bwd(grad_scale, normalization, res, g):
    shape, dtype = res
    scale = grad_scale
    if normalization == "batch":
        scale = grad_scale / shape[0]
    elif normalization == "valid":
        scale = grad_scale / max(int(np.prod(shape)), 1)
    return (jnp.full(shape, scale, dtype=dtype),)


_make_loss_vjp.defvjp(_make_loss_fwd, _make_loss_bwd)


@register("SVMOutput")
def svm_output(data, label, *, margin=1.0, regularization_coefficient=1.0, use_linear=False):
    """SVM output layer (reference src/operator/svm_output.cc).

    Forward = identity on the scores.  Like SoftmaxOutput, the layer
    injects its OWN gradient on backward — one-vs-rest hinge per the
    reference L1_SVM/L2_SVM kernels (svm_output.cc:31-67): the true class
    k gets −c·[margin > s_k] (L1) or −2c·(margin − s_k)·[margin > s_k]
    (L2); every other class j independently gets +c·[margin > −s_j] (L1)
    or +2c·(margin + s_j)·[margin > −s_j] (L2), c = regularization_coefficient.
    """
    return _svm_output_vjp(data, label, float(margin),
                           float(regularization_coefficient), bool(use_linear))


@_partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_output_vjp(data, label, margin, reg, use_linear):
    return data


def _svm_output_fwd(data, label, margin, reg, use_linear):
    return data, (data, label)


def _svm_output_bwd(margin, reg, use_linear, res, g):
    data, label = res
    B, C = data.shape
    y = label.reshape(B).astype(jnp.int32)
    is_true = jnp.arange(C)[None, :] == y[:, None]  # (B, C)
    s = data.astype(jnp.float32)
    if use_linear:
        grad = jnp.where(is_true,
                         -reg * (margin > s).astype(jnp.float32),
                         reg * (margin > -s).astype(jnp.float32))
    else:
        grad = jnp.where(is_true,
                         jnp.where(margin > s, -2.0 * reg * (margin - s), 0.0),
                         jnp.where(margin > -s, 2.0 * reg * (margin + s), 0.0))
    return grad.astype(data.dtype), jnp.zeros_like(label)


_svm_output_vjp.defvjp(_svm_output_fwd, _svm_output_bwd)


# ---------------------------------------------------------------------------
# spatial / misc
# ---------------------------------------------------------------------------


@register("UpSampling")
def upsampling(*args, scale, sample_type="nearest", num_args=1, num_filter=0, multi_input_mode="concat", workspace=512):
    """Upsample (reference src/operator/upsampling.cc). nearest only; bilinear via Deconvolution in reference."""
    data = args[0]
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
        if len(args) > 1:
            outs = [out]
            for extra in args[1:]:
                s = data.shape[2] * scale // extra.shape[2]
                outs.append(jnp.repeat(jnp.repeat(extra, s, axis=2), s, axis=3))
            out = jnp.concatenate(outs, axis=1)
        return out
    if sample_type == "bilinear":
        weight = args[1]
        return deconvolution.op.fn(
            data,
            weight,
            None,
            kernel=(2 * scale - scale % 2,) * 2,
            num_filter=data.shape[1],
            stride=(scale, scale),
            pad=(int(np.ceil((scale - 1) / 2.0)),) * 2,
            num_group=data.shape[1],
            no_bias=True,
        )
    raise ValueError(sample_type)


@register("BilinearSampler")
def bilinear_sampler(data, grid, *, cudnn_off=False):
    """Bilinear sampling by normalized grid (reference src/operator/bilinear_sampler.cc).

    grid: (N, 2, Ho, Wo) in [-1, 1]; out (N, C, Ho, Wo).  Pure gather math —
    XLA lowers the gathers well on TPU; a Pallas variant exists for the
    deformable ops where access is data-dependent per output element.
    """
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def sample(xi, yi):
        xi_c = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yi_c = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        valid = ((xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)).astype(data.dtype)
        # gather per batch: data (N,C,H,W); idx (N,Ho,Wo)
        flat = data.reshape(n, c, h * w)
        idx = (yi_c * w + xi_c).reshape(n, -1)
        vals = jnp.take_along_axis(flat, idx[:, None, :], axis=2)
        return vals.reshape(n, c, *gx.shape[1:]) * valid[:, None]

    v00 = sample(x0, y0)
    v01 = sample(x0 + 1, y0)
    v10 = sample(x0, y0 + 1)
    v11 = sample(x0 + 1, y0 + 1)
    wx_ = wx[:, None]
    wy_ = wy[:, None]
    return (
        v00 * (1 - wx_) * (1 - wy_)
        + v01 * wx_ * (1 - wy_)
        + v10 * (1 - wx_) * wy_
        + v11 * wx_ * wy_
    )


@register("GridGenerator")
def grid_generator(data, *, transform_type, target_shape=(0, 0)):
    """Generate sampling grids (reference src/operator/grid_generator.cc)."""
    if transform_type == "affine":
        n = data.shape[0]
        h, w = target_shape
        theta = data.reshape(n, 2, 3)
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)
        out = jnp.einsum("nij,jk->nik", theta, coords)
        return out.reshape(n, 2, h, w)
    if transform_type == "warp":
        n, _, h, w = data.shape
        gy, gx = jnp.meshgrid(jnp.arange(h, dtype=data.dtype), jnp.arange(w, dtype=data.dtype), indexing="ij")
        x = (data[:, 0] + gx) * 2.0 / max(w - 1, 1) - 1.0
        y = (data[:, 1] + gy) * 2.0 / max(h - 1, 1) - 1.0
        return jnp.stack([x, y], axis=1)
    raise ValueError(transform_type)


@register("SpatialTransformer")
def spatial_transformer(data, loc, *, target_shape, transform_type="affine", sampler_type="bilinear", cudnn_off=False):
    """STN (reference src/operator/spatial_transformer.cc)."""
    grid = grid_generator.op.fn(loc, transform_type=transform_type, target_shape=target_shape)
    return bilinear_sampler.op.fn(data, grid)


@register("SequenceMask")
def sequence_mask(data, sequence_length=None, *, use_sequence_length=False, value=0.0, axis=0):
    """Mask positions past each sequence's length (reference src/operator/sequence_mask.cc).

    data layout: (seq, batch, ...) for axis=0 (MXNet default).
    """
    if not use_sequence_length or sequence_length is None:
        return data
    seq_len = data.shape[axis]
    pos = jnp.arange(seq_len)
    lengths = sequence_length.astype(jnp.int32)
    if axis == 0:
        mask = pos[:, None] < lengths[None, :]  # (seq, batch)
    else:
        mask = pos[None, :] < lengths[:, None]  # (batch, seq)
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceLast")
def sequence_last(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    """Last valid step per sequence (reference src/operator/sequence_last.cc)."""
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    idx = (sequence_length.astype(jnp.int32) - 1)
    batch = data.shape[1 - axis]
    took = jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)) if axis == 0 else idx.reshape((-1, 1) + (1,) * (data.ndim - 2)),
        axis=axis,
    )
    return jnp.squeeze(took, axis=axis)


@register("SequenceReverse")
def sequence_reverse(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    """Reverse sequences up to their length (reference src/operator/sequence_reverse.cc)."""
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    seq_len = data.shape[0]
    pos = jnp.arange(seq_len)[:, None]
    lengths = sequence_length.astype(jnp.int32)[None, :]
    rev_idx = jnp.where(pos < lengths, lengths - 1 - pos, pos)
    return jnp.take_along_axis(data, rev_idx.reshape(rev_idx.shape + (1,) * (data.ndim - 2)), axis=0)
