"""Indexing/gather/scatter ops — reference ``src/operator/tensor/indexing_op.h``
(take, batch_take, Embedding, one_hot, gather_nd, scatter_nd) plus ordering
ops from ``ordering_op-inl.h`` (sort/argsort/topk).

TPU notes: gathers lower to XLA gather (fine on TPU); topk uses lax.top_k
which maps to the TPU sort unit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register
from ..base import dtype_np


@register("take")
def take(a, indices, *, axis=0, mode="clip"):
    """Take elements along axis (reference indexing_op.h Take)."""
    idx = indices.astype(jnp.int32)
    n = a.shape[axis]
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    elif mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    return jnp.take(a, idx, axis=axis)


@register("batch_take")
def batch_take(a, indices):
    """a[i, indices[i]] (reference indexing_op.h batch_take)."""
    return jnp.take_along_axis(a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register(
    "Embedding",
    infer_params=lambda attrs, shapes: {"weight": (attrs["input_dim"], attrs["output_dim"])},
)
def embedding(data, weight, *, input_dim, output_dim, dtype="float32", sparse_grad=False):
    """Embedding lookup (reference indexing_op.h EmbeddingOp).

    TPU note: one_hot-matmul can be faster for small vocab; XLA picks gather
    here which is fine for large vocab.
    """
    idx = jnp.clip(data.astype(jnp.int32), 0, input_dim - 1)
    return jnp.take(weight, idx, axis=0)


@register("one_hot")
def one_hot(indices, *, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    """One-hot encode (reference indexing_op.h OneHot)."""
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=dtype_np(dtype))
    return oh * on_value + (1.0 - oh) * off_value


@register("gather_nd")
def gather_nd(data, indices):
    """Gather by leading-dim index tuples (reference indexing_op.h GatherND).

    indices: (M, ...) int array; output shape indices.shape[1:] + data.shape[M:].
    """
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd")
def scatter_nd(data, indices, *, shape):
    """Scatter values into zeros of `shape` (reference indexing_op.h ScatterND)."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register("_scatter_set_nd")
def _scatter_set_nd(lhs, indices, rhs, *, shape=None):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return lhs.at[tuple(idx[i] for i in range(m))].set(rhs)


# ---------------------------------------------------------------------------
# ordering ops
# ---------------------------------------------------------------------------


@register("sort")
def sort(data, *, axis=-1, is_ascend=True):
    """Sort values (reference ordering_op-inl.h SortOp)."""
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort")
def argsort(data, *, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(dtype_np(dtype))


@register("topk")
def topk(data, *, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """Top-k along axis (reference ordering_op-inl.h TopKOp).

    ret_typ: 'value' | 'indices' | 'mask' | 'both'.
    TPU note: lax.top_k on the last axis maps to the hardware sort unit.
    """
    ax = axis % data.ndim
    x = jnp.moveaxis(data, ax, -1)
    if is_ascend:
        vals, idxs = jax.lax.top_k(-x, k)
        vals = -vals
    else:
        vals, idxs = jax.lax.top_k(x, k)
    vals = jnp.moveaxis(vals, -1, ax)
    idxs = jnp.moveaxis(idxs, -1, ax)
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idxs.astype(dtype_np(dtype))
    if ret_typ == "mask":
        oh = jax.nn.one_hot(
            jnp.moveaxis(idxs, ax, -1).astype(jnp.int32), data.shape[ax], dtype=data.dtype
        )
        return jnp.moveaxis(jnp.sum(oh, axis=-2), -1, ax)
    if ret_typ == "both":
        return vals, idxs.astype(dtype_np(dtype))
    raise ValueError(ret_typ)
