"""On-device RCNN training-target assignment — fixed-capacity, jit-fusable.

The reference computes these targets on the HOST: RPN anchor targets inside
the data loader (``example/rcnn/rcnn/core/loader.py`` AnchorLoader →
``rcnn/io/rpn.py assign_anchor``) and per-ROI targets as a Python CustomOp
(``example/rcnn/rcnn/symbol/proposal_target.py:31,82``, config defaults
``rcnn/config.py:50-66``).  That design forces a device→host→device round
trip in the middle of every training step, which is exactly what kept the
round-1 Deformable R-FCN step eager and host-synced.

TPU-native redesign (SURVEY §7.3 "dynamic shapes" hard part): both ops are
pure jnp with **static output shapes** — candidate sets are fixed capacity,
subsampling is a rank-over-uniform-noise selection (equivalent in
distribution to the reference's ``np.random.choice(..., replace=False)``),
and empty/degenerate cases pad with zero-weight rows exactly where the
reference pads by repetition.  Randomness enters as an explicit ``noise``
input (jax purity); pass fresh uniforms each step when training, or omit it
for deterministic lowest-noise-index selection in tests.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register
from .detection import _generate_base_anchors, _iou_mat


def _iou_plus_one(a, b):
    """IoU with the +1 pixel convention used by the rcnn example's
    bbox_overlaps (``rcnn/processing/bbox_transform.py``) — the shared
    dense-IoU kernel from ops/detection.py."""
    area_a = (a[:, 2] - a[:, 0] + 1.0) * (a[:, 3] - a[:, 1] + 1.0)
    area_b = (b[:, 2] - b[:, 0] + 1.0) * (b[:, 3] - b[:, 1] + 1.0)
    return _iou_mat(a, area_a, b, area_b, plus_one=1.0)


def _bbox_transform(ex, gt):
    """Box regression targets (reference rcnn/processing/bbox_transform.py
    bbox_transform), vectorized over (N, 4) corner boxes."""
    ew = ex[:, 2] - ex[:, 0] + 1.0
    eh = ex[:, 3] - ex[:, 1] + 1.0
    ecx = ex[:, 0] + 0.5 * (ew - 1.0)
    ecy = ex[:, 1] + 0.5 * (eh - 1.0)
    gw = gt[:, 2] - gt[:, 0] + 1.0
    gh = gt[:, 3] - gt[:, 1] + 1.0
    gcx = gt[:, 0] + 0.5 * (gw - 1.0)
    gcy = gt[:, 1] + 0.5 * (gh - 1.0)
    return jnp.stack(
        [
            (gcx - ecx) / (ew + 1e-14),
            (gcy - ecy) / (eh + 1e-14),
            jnp.log(jnp.maximum(gw / ew, 1e-12)),
            jnp.log(jnp.maximum(gh / eh, 1e-12)),
        ],
        axis=1,
    )


def _rank_select(mask, noise, limit):
    """Randomly keep at most ``limit`` True entries of ``mask``.

    Returns (kept_mask, order) where ``order`` lists the kept indices first
    (in noise-rank order).  With uniform iid noise this selection is
    equidistributed with ``np.random.choice(where(mask), limit,
    replace=False)`` — the reference's subsampling primitive.
    """
    n = mask.shape[0]
    key = jnp.where(mask, noise, 2.0)  # non-candidates rank last
    order = jnp.argsort(key, stable=True)
    rank = jnp.zeros((n,), jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    kept = mask & (rank < limit)
    return kept, order


@register("_contrib_rpn_anchor_target")
def rpn_anchor_target(
    gt_boxes,
    im_info,
    noise=None,
    *,
    feat_height,
    feat_width,
    feature_stride=16,
    scales=(8, 16, 32),
    ratios=(0.5, 1, 2),
    allowed_border=0,
    batch_rois=256,
    fg_fraction=0.5,
    pos_iou_thresh=0.7,
    neg_iou_thresh=0.3,
):
    """RPN anchor target assignment, on device (reference host-side
    ``rcnn/io/rpn.py assign_anchor`` driven by AnchorLoader; config defaults
    ``rcnn/config.py:60-66`` RPN_BATCH_SIZE/RPN_FG_FRACTION/..._OVERLAP).

    Inputs: ``gt_boxes`` (B, G, 5) rows [cls, x1, y1, x2, y2] padded with
    −1; ``im_info`` (B, 3) [h, w, scale]; ``noise`` (B, A_total, 2) iid
    uniforms driving fg/bg subsampling (omit for deterministic selection).
    Outputs: label (B, A_total) ∈ {−1 ignore, 0 bg, 1 fg}, bbox_target
    (B, A_total, 4), bbox_weight (B, A_total, 4) — anchor index order is
    ``h·(W·A) + w·A + a``, matching MultiProposal's enumeration.
    """
    Hf, Wf = int(feat_height), int(feat_width)
    stride = float(feature_stride)
    base = jnp.asarray(_generate_base_anchors(stride, scales, ratios))
    A = base.shape[0]
    total = Hf * Wf * A
    max_fg = int(round(batch_rois * fg_fraction))

    shift_x = jnp.arange(Wf, dtype=jnp.float32) * stride
    shift_y = jnp.arange(Hf, dtype=jnp.float32) * stride
    shifts = jnp.stack(
        [
            jnp.broadcast_to(shift_x[None, :, None], (Hf, Wf, A)),
            jnp.broadcast_to(shift_y[:, None, None], (Hf, Wf, A)),
            jnp.broadcast_to(shift_x[None, :, None], (Hf, Wf, A)),
            jnp.broadcast_to(shift_y[:, None, None], (Hf, Wf, A)),
        ],
        axis=-1,
    )
    anchors = (shifts + base[None, None, :, :]).reshape(total, 4)

    if noise is None:
        # deterministic: prefer low anchor index (tests / reproducibility)
        noise = jnp.broadcast_to(
            (jnp.arange(total, dtype=jnp.float32) / (total + 1.0))[None, :, None],
            (gt_boxes.shape[0], total, 2),
        )

    def one(gt, info, nz):
        im_h, im_w = info[0], info[1]
        inside = (
            (anchors[:, 0] >= -allowed_border)
            & (anchors[:, 1] >= -allowed_border)
            & (anchors[:, 2] < im_w + allowed_border)
            & (anchors[:, 3] < im_h + allowed_border)
        )
        gt_valid = gt[:, 0] >= 0  # (G,)
        num_gt = gt_valid.sum()
        iou = _iou_plus_one(anchors, gt[:, 1:5])  # (total, G)
        iou = jnp.where(gt_valid[None, :] & inside[:, None], iou, -1.0)
        argmax = jnp.argmax(iou, axis=1)
        max_iou = jnp.maximum(jnp.max(iou, axis=1), 0.0)

        fg = inside & (max_iou >= pos_iou_thresh) & (num_gt > 0)
        # each valid gt's best anchor is fg (reference assign_anchor rule);
        # iou already −1 outside/invalid so the argmax lands inside.
        # scatter-add (not set) so duplicate best-anchor indices stay correct
        gt_best = jnp.argmax(iou, axis=0)  # (G,)
        is_best = (
            jnp.zeros((total,), jnp.int32).at[gt_best].add(gt_valid.astype(jnp.int32)) > 0
        )
        fg = fg | (is_best & inside)
        fg_kept, _ = _rank_select(fg, nz[:, 0], max_fg)
        n_fg = fg_kept.sum()

        bg = inside & (max_iou < neg_iou_thresh) & ~fg & (num_gt > 0)
        # no gt at all: every inside anchor is a bg candidate
        bg = jnp.where(num_gt > 0, bg, inside)
        max_bg = batch_rois - jnp.minimum(n_fg, max_fg)
        bg_kept, _ = _rank_select(bg, nz[:, 1], max_bg)

        label = jnp.where(fg_kept, 1.0, jnp.where(bg_kept, 0.0, -1.0))
        safe_gt = jnp.clip(argmax, 0, gt.shape[0] - 1)
        tgt = _bbox_transform(anchors, gt[safe_gt, 1:5])
        w = fg_kept[:, None].astype(jnp.float32)
        return label, tgt * w, jnp.broadcast_to(w, (total, 4))

    return jax.vmap(one)(gt_boxes, im_info, noise)


@register("_contrib_proposal_target")
def proposal_target(
    rois,
    gt_boxes,
    noise=None,
    *,
    num_classes,
    batch_images,
    batch_rois=128,
    fg_fraction=0.25,
    fg_overlap=0.5,
    class_agnostic=False,
    box_stds=None,
):
    """Per-ROI training targets, on device (reference CustomOp
    ``rcnn/symbol/proposal_target.py:31-110`` + ``rcnn/io/rcnn.py
    sample_rois``; config ``rcnn/config.py:50-56`` BATCH_ROIS=128,
    FG_FRACTION=0.25, FG_THRESH=0.5, BG=[0, 0.5)).

    ``box_stds``: per-coordinate target scaling (reference
    TRAIN.BBOX_NORMALIZATION_PRECOMPUTED + BBOX_STDS (0.1, 0.1, 0.2, 0.2),
    enabled by ``train_end2end.py:38``); targets are divided by the stds so
    the regression head trains on ~unit-variance values, and inference
    multiplies predictions back.

    Inputs: ``rois`` (B·post, 5) [batch_idx|x1..y2] batch-major (the
    MultiProposal layout); ``gt_boxes`` (B, G, 5) [cls, x1, y1, x2, y2]
    padded with −1; ``noise`` (B, post+G, 2) iid uniforms.  Ground-truth
    boxes join the candidate set (reference proposal_target.py:54-56).

    Outputs (all static): rois_out (batch_rois, 5), label (batch_rois,),
    bbox_target and bbox_weight (batch_rois, 4·K) where K = num_classes
    (incl. background) or 2 when ``class_agnostic`` (Deformable R-FCN's
    head regresses 2 classes: bg/fg).  Degenerate images (no candidates)
    emit zero-weight background rows — gradient-free padding where the
    reference pads by repeating sampled indices.
    """
    B = int(batch_images)
    C = int(num_classes)
    K = 2 if class_agnostic else C
    per_im = int(batch_rois) // B
    if per_im * B != int(batch_rois):
        raise ValueError(
            "batch_rois (%d) must be divisible by batch_images (%d)"
            % (batch_rois, batch_images))
    fg_per_im = int(round(fg_fraction * per_im))
    post = rois.shape[0] // B
    G = gt_boxes.shape[1]
    ncand = post + G

    rois_b = rois.reshape(B, post, 5)
    if noise is None:
        noise = jnp.broadcast_to(
            (jnp.arange(ncand, dtype=jnp.float32) / (ncand + 1.0))[None, :, None],
            (B, ncand, 2),
        )

    def one(b, rb, gt, nz):
        gt_valid = gt[:, 0] >= 0
        num_gt = gt_valid.sum()
        # candidates: proposals then gt boxes (zero-weight pad rows for
        # invalid gts — they can never be sampled)
        gt_rows = jnp.concatenate(
            [jnp.full((G, 1), b, rois.dtype), gt[:, 1:5]], axis=1)
        cand = jnp.concatenate([rb, gt_rows], axis=0)  # (ncand, 5)
        cand_valid = jnp.concatenate(
            [jnp.ones((post,), bool), gt_valid], axis=0)

        iou = _iou_plus_one(cand[:, 1:5], gt[:, 1:5])  # (ncand, G)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        argmax = jnp.clip(jnp.argmax(iou, axis=1), 0, G - 1)
        max_iou = jnp.maximum(jnp.max(iou, axis=1), 0.0)

        fg = cand_valid & (max_iou >= fg_overlap) & (num_gt > 0)
        fg_kept, fg_order = _rank_select(fg, nz[:, 0], fg_per_im)
        n_fg = jnp.minimum(fg_kept.sum(), fg_per_im)

        bg = cand_valid & (max_iou < fg_overlap)
        bg_kept, bg_order = _rank_select(bg, nz[:, 1], per_im - n_fg)
        n_bg = jnp.minimum(bg_kept.sum(), per_im - n_fg)

        # slot i: i-th sampled fg, then sampled bgs cycled to capacity.  A
        # bg-starved image (every proposal ≥ fg_overlap) cycles the sampled
        # fgs instead — the reference pads by repeating sampled indices WITH
        # their true labels (rcnn/io/rcnn.py sample_rois), so labels/weights
        # below derive from the candidate's own IoU, not its slot.
        slots = jnp.arange(per_im)
        bg_slot = (slots - n_fg) % jnp.maximum(n_bg, 1)
        fg_pad_slot = slots % jnp.maximum(n_fg, 1)
        pad_idx = jnp.where(n_bg > 0, bg_order[bg_slot], fg_order[fg_pad_slot])
        idx = jnp.where(slots < n_fg, fg_order[slots], pad_idx)
        sel = cand[idx]
        sel_gt = argmax[idx]
        is_fg = fg[idx]  # candidate quality, not slot position
        label = jnp.where(is_fg, gt[sel_gt, 0] + 1.0, 0.0)  # 0 = background

        tgt = _bbox_transform(sel[:, 1:5], gt[sel_gt, 1:5])  # (per_im, 4)
        if box_stds is not None:
            tgt = tgt / jnp.asarray(box_stds, tgt.dtype)[None, :]
        kcls = (jnp.minimum(label, 1.0) if class_agnostic else label).astype(jnp.int32)
        onehot = jax.nn.one_hot(kcls, K, dtype=rois.dtype)  # (per_im, K)
        w = is_fg[:, None, None] * onehot[:, :, None]  # (per_im, K, 1)
        bbox_target = (w * tgt[:, None, :]).reshape(per_im, 4 * K)
        bbox_weight = jnp.broadcast_to(w, (per_im, K, 4)).reshape(per_im, 4 * K)
        return sel, label, bbox_target, bbox_weight

    sel, label, bt, bw = jax.vmap(one)(
        jnp.arange(B, dtype=rois.dtype), rois_b, gt_boxes, noise)
    return (
        sel.reshape(B * per_im, 5),
        label.reshape(B * per_im),
        bt.reshape(B * per_im, 4 * K),
        bw.reshape(B * per_im, 4 * K),
    )
