"""INT8 quantization operators — reference ``src/operator/quantization/``
(quantize-inl.h:53-80, dequantize-inl.h, requantize-inl.h,
quantized_conv.cc, quantized_fully_connected.cc, quantized_pooling.cc,
quantized_flatten-inl.h, quantization_utils.h).

TPU-native: int8 operands feed the MXU with int32 accumulation
(``preferred_element_type=int32``); ranges are tracked as scalar (1,)
tensors exactly like the reference's min/max companion outputs, so the
same graph-rewrite pass (contrib/quantization.py) applies.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register
from .nn import _tup

INT32_MAX = float(2**31 - 1)
INT32_MIN = float(-(2**31 - 1))


def _qrange(out_type):
    """(min_limit, max_limit, quantized_range) per reference
    quantization_utils.h FloatForOneQuantizedLevel."""
    if out_type == "uint8":
        return 0.0, 255.0, 255.0
    if out_type == "int8":
        return -127.0, 127.0, 127.0
    raise ValueError("unsupported quantized type %r" % (out_type,))


def _maxabs(a, b):
    return jnp.maximum(jnp.abs(a), jnp.abs(b))


@register("_contrib_quantize", alias=["quantize"])
def quantize(data, min_range, max_range, *, out_type="uint8"):
    """float32 -> quantized (reference quantize-inl.h:53-80).

    uint8: affine over [min_range, max_range]; int8: symmetric over
    [-maxabs, maxabs]. Returns (q, min_out, max_out)."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(()) if np.ndim(min_range) == 0 else min_range.reshape(()).astype(jnp.float32)
    mx_ = jnp.asarray(max_range, jnp.float32).reshape(()) if np.ndim(max_range) == 0 else max_range.reshape(()).astype(jnp.float32)
    if out_type == "uint8":
        lo, hi, qrange = _qrange("uint8")
        scale = qrange / (mx_ - mn)
        # the quantize OP's whole job is this narrowing (reference
        # quantize-inl.h); range/scale saturate first
        q = jnp.clip((data - mn) * scale + 0.5, lo, hi).astype(
            jnp.uint8)  # mxlint: ignore[implicit-downcast]
        return q, mn.reshape(1), mx_.reshape(1)
    real_range = _maxabs(mn, mx_)
    from .pallas_kernels import quantize_int8_pallas, supported as _pallas_ok

    if jax.default_backend() == "tpu" and _pallas_ok(data.shape, data.dtype):
        q = quantize_int8_pallas(data, real_range)
    else:
        scale = 127.0 / real_range
        # symmetric int8 quantize: the cast IS the operator contract,
        # saturated at +-127 first
        q = (jnp.sign(data) * jnp.minimum(jnp.abs(data) * scale + 0.5, 127.0)).astype(jnp.int8)  # mxlint: ignore[implicit-downcast]
    return q, (-real_range).reshape(1), real_range.reshape(1)


@register("_contrib_dequantize", alias=["dequantize"])
def dequantize(data, min_range, max_range, *, out_type="float32"):
    """quantized -> float32 (reference dequantize-inl.h)."""
    mn = min_range.reshape(()).astype(jnp.float32)
    mx_ = max_range.reshape(()).astype(jnp.float32)
    if data.dtype == jnp.uint8:
        scale = (mx_ - mn) / 255.0
        return data.astype(jnp.float32) * scale + mn
    if data.dtype == jnp.int32:
        real = _maxabs(mn, mx_)
        return data.astype(jnp.float32) * (real / INT32_MAX)
    real = _maxabs(mn, mx_)
    from .pallas_kernels import dequantize_int8_pallas, supported as _pallas_ok

    if (data.dtype == jnp.int8 and jax.default_backend() == "tpu"
            and _pallas_ok(data.shape, data.dtype)):
        return dequantize_int8_pallas(data, real)
    return data.astype(jnp.float32) * (real / 127.0)


@register("_contrib_requantize", alias=["requantize"])
def requantize(data, min_range, max_range, *, min_calib_range=None, max_calib_range=None):
    """int32 -> int8 re-quantization (reference requantize-inl.h). Without
    calibrated ranges the actual min/max of the tensor is used (the
    reference's runtime path)."""
    real_in = _maxabs(min_range.reshape(()), max_range.reshape(())).astype(jnp.float32)
    fval = data.astype(jnp.float32) * (real_in / INT32_MAX)
    if min_calib_range is not None and max_calib_range is not None:
        real_out = jnp.maximum(abs(float(min_calib_range)), abs(float(max_calib_range)))
        real_out = jnp.asarray(real_out, jnp.float32)
    else:
        real_out = jnp.max(jnp.abs(fval))
    scale = 127.0 / real_out
    # int32->int8 requantize: narrowing is the op's documented output
    # contract, saturated first
    q = (jnp.sign(fval) * jnp.minimum(jnp.abs(fval) * scale + 0.5, 127.0)).astype(jnp.int8)  # mxlint: ignore[implicit-downcast]
    return q, (-real_out).reshape(1), real_out.reshape(1)


def _float_for_one(min_r, max_r, dtype):
    """Float value of one quantized level. int8 is symmetric (maxabs/127);
    uint8 is affine ((max-min)/255) with zero-point min (reference
    quantization_utils.h + the MKLDNN affine path)."""
    mn = min_r.reshape(())
    mx_ = max_r.reshape(())
    if dtype == jnp.uint8:
        return (mx_ - mn) / 255.0
    return _maxabs(mn, mx_) / 127.0


def _range_for_mul(a_one, b_one):
    """int32-accumulator output range (reference quantization_utils.h
    QuantizationRangeForMultiplication)."""
    one = (a_one * b_one).astype(jnp.float32)
    return (one * INT32_MIN).reshape(1), (one * INT32_MAX).reshape(1)


def _qconv_inputs(attrs):
    # bias triple trails so that no_bias only drops TRAILING positionals
    # (the executor and shape inference pass inputs positionally)
    base = ["data", "weight", "min_data", "max_data", "min_weight", "max_weight"]
    if not attrs.get("no_bias"):
        base += ["bias", "min_bias", "max_bias"]
    return base


def _q_minmax_shapes(attrs):
    names = ["min_data", "max_data", "min_weight", "max_weight"]
    if not attrs.get("no_bias"):
        names += ["min_bias", "max_bias"]
    return {n: (1,) for n in names}


def _qconv_params(attrs, shapes):
    from .nn import _conv_params

    out = _conv_params(attrs, shapes)
    out.update(_q_minmax_shapes(attrs))
    return out


def _qfc_params(attrs, shapes):
    from .nn import _fc_params

    out = _fc_params(attrs, shapes)
    out.update(_q_minmax_shapes(attrs))
    return out


@register("_contrib_quantized_conv", alias=["quantized_conv"], inputs_fn=_qconv_inputs,
          infer_params=_qconv_params)
def quantized_conv(data, weight, min_data=None, max_data=None,
                   min_weight=None, max_weight=None, bias=None, min_bias=None,
                   max_bias=None, *, kernel, num_filter, stride=None, pad=None,
                   dilate=None, no_bias=False, num_group=1, layout="NCHW",
                   cudnn_off=False, cudnn_tune=None, workspace=1024):
    """int8 convolution with int32 accumulation (reference quantized_conv.cc).
    Returns (int32 out, min_out, max_out)."""
    k = _tup(kernel, 2)
    assert len(k) == 2, "quantized conv is 2D (reference quantized_conv.cc)"
    s = _tup(stride, 2)
    p = _tup(pad if pad is not None else 0, 2)
    d = _tup(dilate, 2)
    lhs = data.astype(jnp.int32)
    rhs = weight.astype(jnp.int32)
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=s, padding=[(pi, pi) for pi in p],
        rhs_dilation=d, feature_group_count=num_group,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    a_one = _float_for_one(min_data, max_data, data.dtype)
    w_one = _float_for_one(min_weight, max_weight, weight.dtype)
    if data.dtype == jnp.uint8:
        # affine zero-point: x = q*a_one + min_d inside the image but exactly 0
        # in padding, so the min_d*sum(w) correction is per-position — a mask
        # convolution over the valid window (XLA folds it; it's weight-only)
        z = jnp.round(min_data.reshape(()) / a_one).astype(jnp.int32)
        mask = jnp.ones_like(lhs)
        win_w = jax.lax.conv_general_dilated(
            mask, rhs, window_strides=s, padding=[(pi, pi) for pi in p],
            rhs_dilation=d, feature_group_count=num_group,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        out = out + z * win_w
    acc_one = (a_one * w_one).astype(jnp.float32)
    mn, mx_ = _range_for_mul(a_one, w_one)
    if bias is not None and not no_bias:
        # rescale int8 bias into the int32 accumulator's quantization level
        bias_one = _maxabs(min_bias.reshape(()), max_bias.reshape(())) / 127.0
        bias32 = jnp.round(bias.astype(jnp.float32) * (bias_one / acc_one)).astype(jnp.int32)
        out = out + bias32.reshape((1, -1) + (1,) * len(k))
    return out, mn, mx_


@register("_contrib_quantized_fully_connected", alias=["quantized_fully_connected"], inputs_fn=_qconv_inputs,
          infer_params=_qfc_params)
def quantized_fully_connected(data, weight, min_data=None, max_data=None,
                              min_weight=None, max_weight=None, bias=None,
                              min_bias=None, max_bias=None, *, num_hidden,
                              no_bias=False, flatten=True):
    """int8 dense with int32 accumulation (reference
    quantized_fully_connected.cc). Returns (int32 out, min_out, max_out)."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    xq = x.astype(jnp.int32)
    wq = weight.astype(jnp.int32)
    out = jax.lax.dot_general(xq, wq, (((x.ndim - 1,), (1,)), ((), ())))
    a_one = _float_for_one(min_data, max_data, data.dtype)
    w_one = _float_for_one(min_weight, max_weight, weight.dtype)
    if data.dtype == jnp.uint8:
        z = jnp.round(min_data.reshape(()) / a_one).astype(jnp.int32)
        out = out + z * jnp.sum(wq, axis=1)
    acc_one = (a_one * w_one).astype(jnp.float32)
    mn, mx_ = _range_for_mul(a_one, w_one)
    if bias is not None and not no_bias:
        bias_one = _maxabs(min_bias.reshape(()), max_bias.reshape(())) / 127.0
        bias32 = jnp.round(bias.astype(jnp.float32) * (bias_one / acc_one)).astype(jnp.int32)
        out = out + bias32
    return out, mn, mx_


@register("_contrib_quantized_pooling", alias=["quantized_pooling"])
def quantized_pooling(data, min_data, max_data, *, kernel=(1, 1), pool_type="max",
                      stride=None, pad=None, global_pool=False,
                      pooling_convention="valid", count_include_pad=True,
                      cudnn_off=False, p_value=2, layout=None):
    """Pooling on quantized data; range passes through (reference
    quantized_pooling.cc). max/avg are linear in the quantized encoding
    (affine for uint8, symmetric for int8), so the float kernel applies
    verbatim. Returns (q out, min, max)."""
    from .nn import pooling

    if pool_type not in ("max", "avg"):
        raise ValueError("unsupported quantized pool_type %r" % pool_type)
    out = pooling(
        data, kernel=kernel, pool_type=pool_type, global_pool=global_pool,
        stride=stride, pad=pad, pooling_convention=pooling_convention,
        count_include_pad=count_include_pad, p_value=p_value, layout=layout,
    )
    return out, min_data, max_data


@register("_contrib_quantized_flatten", alias=["quantized_flatten"])
def quantized_flatten(data, min_data, max_data):
    """Flatten on quantized data (reference quantized_flatten-inl.h)."""
    return data.reshape(data.shape[0], -1), min_data, max_data


@register("_contrib_quantized_act", alias=["quantized_act"])
def quantized_act(data, min_data, max_data, *, act_type="relu"):
    """ReLU on symmetric int8 keeps the range representation (zero stays
    zero); other activations must be computed in float."""
    if act_type != "relu":
        raise ValueError("only relu supported in the quantized domain")
    if data.dtype == jnp.uint8:
        raise ValueError("relu on affine uint8 needs the zero point; compute in float")
    return jnp.maximum(data, 0), min_data, max_data
