"""Operator library — pure jax functions registered by name.

Importing ``_load_all`` (done by the nd/sym frontends) populates the registry
with every op family, the TPU-native equivalent of the reference's static
NNVM_REGISTER_OP initializers under src/operator/.
"""
from . import registry  # noqa: F401

from . import elemwise  # noqa: F401
from . import matrix  # noqa: F401
from . import reduce  # noqa: F401
from . import init_ops  # noqa: F401
from . import indexing  # noqa: F401
from . import nn  # noqa: F401
from . import random_ops  # noqa: F401
from . import rnn  # noqa: F401
from . import linalg  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import contrib_ops  # noqa: F401
from . import quantization  # noqa: F401
from . import misc_ops  # noqa: F401
from . import detection  # noqa: F401
from . import rcnn_targets  # noqa: F401
from . import custom  # noqa: F401

_load_all = True
