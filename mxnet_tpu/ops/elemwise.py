"""Elementwise unary/binary/scalar operators.

TPU-native equivalents of reference ``src/operator/tensor/elemwise_*`` and the
mshadow functor library (``src/operator/mshadow_op.h``).  Every op is a pure
jnp function; XLA fuses chains of these into single kernels (replacing the
reference's manual Kernel<op,xpu>::Launch dispatch + engine bulking).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _unary(name, fn, aliases=(), doc=None):
    def op(data):
        return fn(data)

    op.__name__ = name.lstrip("_")
    op.__qualname__ = op.__name__
    op.__doc__ = doc or ("Elementwise %s. Reference: src/operator/tensor/elemwise_unary_op_basic.cc" % name)
    register(name, alias=aliases)(op)
    return op


def _binary(name, fn, aliases=(), doc=None):
    def op(lhs, rhs):
        return fn(lhs, rhs)

    op.__name__ = name.lstrip("_")
    op.__qualname__ = op.__name__
    op.__doc__ = doc or ("Elementwise binary %s (auto-broadcasting). Reference: src/operator/tensor/elemwise_binary_op_basic.cc" % name)
    register(name, alias=aliases)(op)
    return op


def _scalar_op(name, fn, aliases=()):
    def op(data, *, scalar):
        return fn(data, scalar)

    op.__name__ = name.lstrip("_")
    op.__qualname__ = op.__name__
    op.__doc__ = "Scalar %s. Reference: src/operator/tensor/elemwise_binary_scalar_op_basic.cc" % name
    register(name, alias=aliases)(op)
    return op


# ---------------------------------------------------------------------------
# binary (the reference distinguishes elemwise_* [same-shape] from broadcast_*;
# both map to jnp broadcasting semantics, registered under both families)
# ---------------------------------------------------------------------------

_binary("elemwise_add", jnp.add, aliases=["_plus", "_add"])
_binary("elemwise_sub", jnp.subtract, aliases=["_minus", "_sub"])
_binary("elemwise_mul", jnp.multiply, aliases=["_mul"])
_binary("elemwise_div", jnp.divide, aliases=["_div"])
_binary("_mod", jnp.mod)
_binary("_power", jnp.power, aliases=["_pow"])
_binary("_maximum", jnp.maximum, aliases=["_max"])
_binary("_minimum", jnp.minimum, aliases=["_min"])
_binary("_hypot", jnp.hypot)
_binary("_equal", lambda a, b: (a == b).astype(_cmp_dtype(a)))
_binary("_not_equal", lambda a, b: (a != b).astype(_cmp_dtype(a)))
_binary("_greater", lambda a, b: (a > b).astype(_cmp_dtype(a)))
_binary("_greater_equal", lambda a, b: (a >= b).astype(_cmp_dtype(a)))
_binary("_lesser", lambda a, b: (a < b).astype(_cmp_dtype(a)))
_binary("_lesser_equal", lambda a, b: (a <= b).astype(_cmp_dtype(a)))
_binary("_logical_and", lambda a, b: jnp.logical_and(a, b).astype(_cmp_dtype(a)))
_binary("_logical_or", lambda a, b: jnp.logical_or(a, b).astype(_cmp_dtype(a)))
_binary("_logical_xor", lambda a, b: jnp.logical_xor(a, b).astype(_cmp_dtype(a)))


def _cmp_dtype(a):
    # MXNet comparisons return same-dtype 0/1 arrays (float32 typically)
    dt = jnp.asarray(a).dtype
    return dt if jnp.issubdtype(dt, jnp.floating) else jnp.float32


broadcast_names = [
    ("broadcast_add", jnp.add, ["broadcast_plus"]),
    ("broadcast_sub", jnp.subtract, ["broadcast_minus"]),
    ("broadcast_mul", jnp.multiply, []),
    ("broadcast_div", jnp.divide, []),
    ("broadcast_mod", jnp.mod, []),
    ("broadcast_power", jnp.power, []),
    ("broadcast_maximum", jnp.maximum, []),
    ("broadcast_minimum", jnp.minimum, []),
    ("broadcast_hypot", jnp.hypot, []),
    ("broadcast_equal", lambda a, b: (a == b).astype(_cmp_dtype(a)), []),
    ("broadcast_not_equal", lambda a, b: (a != b).astype(_cmp_dtype(a)), []),
    ("broadcast_greater", lambda a, b: (a > b).astype(_cmp_dtype(a)), []),
    ("broadcast_greater_equal", lambda a, b: (a >= b).astype(_cmp_dtype(a)), []),
    ("broadcast_lesser", lambda a, b: (a < b).astype(_cmp_dtype(a)), []),
    ("broadcast_lesser_equal", lambda a, b: (a <= b).astype(_cmp_dtype(a)), []),
    ("broadcast_logical_and", lambda a, b: jnp.logical_and(a, b).astype(_cmp_dtype(a)), []),
    ("broadcast_logical_or", lambda a, b: jnp.logical_or(a, b).astype(_cmp_dtype(a)), []),
    ("broadcast_logical_xor", lambda a, b: jnp.logical_xor(a, b).astype(_cmp_dtype(a)), []),
]
for _n, _f, _a in broadcast_names:
    _binary(_n, _f, aliases=_a)

# ---------------------------------------------------------------------------
# scalar ops
# ---------------------------------------------------------------------------

_scalar_op("_plus_scalar", lambda x, s: x + s)
_scalar_op("_minus_scalar", lambda x, s: x - s)
_scalar_op("_rminus_scalar", lambda x, s: s - x)
_scalar_op("_mul_scalar", lambda x, s: x * s)
_scalar_op("_div_scalar", lambda x, s: x / s)
_scalar_op("_rdiv_scalar", lambda x, s: s / x)
_scalar_op("_mod_scalar", lambda x, s: jnp.mod(x, s))
_scalar_op("_rmod_scalar", lambda x, s: jnp.mod(jnp.full_like(x, s), x) if not jnp.isscalar(s) else jnp.mod(s, x))
_scalar_op("_power_scalar", lambda x, s: jnp.power(x, s))
_scalar_op("_rpower_scalar", lambda x, s: jnp.power(s, x))
_scalar_op("_maximum_scalar", lambda x, s: jnp.maximum(x, s))
_scalar_op("_minimum_scalar", lambda x, s: jnp.minimum(x, s))
_scalar_op("_hypot_scalar", lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)))
_scalar_op("_equal_scalar", lambda x, s: (x == s).astype(_cmp_dtype(x)))
_scalar_op("_not_equal_scalar", lambda x, s: (x != s).astype(_cmp_dtype(x)))
_scalar_op("_greater_scalar", lambda x, s: (x > s).astype(_cmp_dtype(x)))
_scalar_op("_greater_equal_scalar", lambda x, s: (x >= s).astype(_cmp_dtype(x)))
_scalar_op("_lesser_scalar", lambda x, s: (x < s).astype(_cmp_dtype(x)))
_scalar_op("_lesser_equal_scalar", lambda x, s: (x <= s).astype(_cmp_dtype(x)))
_scalar_op("_logical_and_scalar", lambda x, s: jnp.logical_and(x, s).astype(_cmp_dtype(x)))
_scalar_op("_logical_or_scalar", lambda x, s: jnp.logical_or(x, s).astype(_cmp_dtype(x)))
_scalar_op("_logical_xor_scalar", lambda x, s: jnp.logical_xor(x, s).astype(_cmp_dtype(x)))

# ---------------------------------------------------------------------------
# unary math (reference src/operator/mshadow_op.h functor zoo)
# ---------------------------------------------------------------------------

_unary("negative", jnp.negative, aliases=["_np_negative"])
_unary("reciprocal", jnp.reciprocal)
_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("round", jnp.round)
_unary("rint", jnp.rint)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("trunc", jnp.trunc)
_unary("fix", jnp.trunc)
_unary("square", jnp.square)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lambda x: jax.lax.rsqrt(x))
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log10", jnp.log10)
_unary("log2", jnp.log2)
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)
_unary("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
_unary("gammaln", lambda x: jax.scipy.special.gammaln(x))
_unary("erf", lambda x: jax.scipy.special.erf(x))
_unary("erfinv", lambda x: jax.scipy.special.erfinv(x))
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("logical_not", lambda x: jnp.logical_not(x).astype(_cmp_dtype(x)))
_unary("relu", lambda x: jnp.maximum(x, 0))
_unary("sigmoid", jax.nn.sigmoid)
_unary("softsign", jax.nn.soft_sign)
_unary("_copy", lambda x: x, aliases=["identity"])
_unary(
    "BlockGrad",
    jax.lax.stop_gradient,
    aliases=["stop_gradient"],
    doc="Stop gradient flow (reference BlockGrad / make_loss.cc). Maps to lax.stop_gradient.",
)


@register("clip")
def clip(data, *, a_min, a_max):
    """Clip values to [a_min, a_max]. Reference: src/operator/tensor/matrix_op.cc clip."""
    return jnp.clip(data, a_min, a_max)


@register("smooth_l1")
def smooth_l1(data, *, scalar=1.0):
    """Smooth L1 loss transform (reference mshadow_op.h smooth_l1_loss; rcnn bbox regression)."""
    sigma2 = scalar * scalar
    absd = jnp.abs(data)
    return jnp.where(absd < 1.0 / sigma2, 0.5 * sigma2 * data * data, absd - 0.5 / sigma2)


@register("add_n", alias=["ElementWiseSum", "_sum"])
def add_n(*args):
    """Sum of n arrays (reference src/operator/tensor/elemwise_sum.cc)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("_scatter_elemwise_div")
def _scatter_elemwise_div(lhs, rhs):
    return lhs / rhs


@register("cast", alias=["Cast"])
def cast(data, *, dtype):
    """Cast dtype (reference elemwise_unary_op_basic.cc Cast)."""
    from ..base import dtype_np

    return data.astype(dtype_np(dtype))


@register("_zeros_like", alias=["zeros_like"])
def zeros_like(data):
    return jnp.zeros_like(data)


@register("_ones_like", alias=["ones_like"])
def ones_like(data):
    return jnp.ones_like(data)


@register("_maximum_mask_scalar")
def _maximum_mask_scalar(data, *, scalar):
    return (data >= scalar).astype(data.dtype)
