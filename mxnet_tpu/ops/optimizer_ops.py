"""Optimizer update operators — reference ``src/operator/optimizer_op.cc``
(sgd_update :317, sgd_mom_update :344, mp_* :398-431, ftml_update :433,
adam_update :465, rmsprop_update :519, rmspropalex_update :569,
ftrl_update :610, signsgd_update :43, signum_update :69).

The reference's kernels mutate weight/state tensors in place; here each op is
a pure function returning (new_weight, *new_states) and the eager frontend
writes the extra outputs back into the passed-in state NDArrays (OpDef
``mutates``), so ``nd.adam_update(w, g, m, v, out=w, lr=...)`` behaves like
the reference. On TPU these fuse into a handful of HBM-bound elementwise
kernels under jit.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _prep_grad(grad, rescale_grad, clip_gradient, wd, weight):
    """sgd-family semantics: clip(rescale*grad) + wd*weight
    (reference SGDKernel optimizer_op-inl.h:92-96)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


def _prep_grad_clip_after(grad, rescale_grad, clip_gradient, wd, weight):
    """adam/rmsprop-family semantics: clip(rescale*grad + wd*weight)
    (reference AdamUpdate optimizer_op-inl.h:841+ adds wd before clipping)."""
    g = grad * rescale_grad + wd * weight
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register("sgd_update")
def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
               lazy_update=True):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * g


@register("sgd_mom_update", mutates=("mom",))
def sgd_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("mp_sgd_update", mutates=("weight32",))
def mp_sgd_update(weight, grad, weight32, *, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """Multi-precision SGD: fp32 master weights, low-precision model weights
    (reference optimizer_op.cc:398)."""
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient, wd, weight32)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", mutates=("mom", "weight32"))
def mp_sgd_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient, wd, weight32)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("adam_update", mutates=("mean", "var"))
def adam_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999, epsilon=1e-8,
                wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad_clip_after(grad, rescale_grad, clip_gradient, wd, weight)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * g * g
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w, new_mean, new_var


@register("ftml_update", mutates=("d", "v", "z"))
def ftml_update(weight, grad, d, v, z, *, lr, t, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
    """FTML (reference optimizer_op.cc:433; Zheng & Kwok 2017)."""
    g = grad * rescale_grad + wd * weight
    if clip_grad is not None and clip_grad >= 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    new_v = beta2 * v + (1.0 - beta2) * g * g
    d_t = (1.0 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1.0 - beta2 ** t)) + epsilon)
    sigma_t = d_t - beta1 * d
    new_z = beta1 * z + (1.0 - beta1) * g - sigma_t * weight
    new_w = -new_z / d_t
    return new_w, d_t, new_v, new_z


@register("rmsprop_update", mutates=("n",))
def rmsprop_update(weight, grad, n, *, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _prep_grad_clip_after(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = (1.0 - gamma1) * g * g + gamma1 * n
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", mutates=("n", "g", "delta"))
def rmspropalex_update(weight, grad, n, g, delta, *, lr, gamma1=0.95, gamma2=0.9,
                       epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                       clip_weights=-1.0):
    """RMSProp with momentum (Graves 2013; reference optimizer_op.cc:569)."""
    gr = _prep_grad_clip_after(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = (1.0 - gamma1) * gr * gr + gamma1 * n
    new_g = (1.0 - gamma1) * gr + gamma1 * g
    new_delta = gamma2 * delta - lr * gr / jnp.sqrt(new_n - new_g * new_g + epsilon)
    new_w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


@register("ftrl_update", mutates=("z", "n"))
def ftrl_update(weight, grad, z, n, *, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + g * g
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd),
    )
    return new_w, new_z, new_n


@register("signsgd_update")
def signsgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


def adam_bias_corrected_lr(lr, t, beta1=0.9, beta2=0.999):
    """Fold adam's step-``t`` bias correction into the learning rate
    (``lr * sqrt(1-b2^t)/(1-b1^t)``, the ``optimizer.adam_rule`` schedule) so
    the in-graph :func:`adam_update` kernel stays schedule-free.  Host-side
    math on Python floats — the fused step passes the result in through its
    traced per-parameter lr vector, so advancing ``t`` never retraces."""
    return lr * (1.0 - beta2 ** t) ** 0.5 / (1.0 - beta1 ** t)


def fused_update(kind, weight, grad, state, *, lr, wd, rescale_grad=1.0,
                 clip_gradient=-1.0, momentum=0.0, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
    """Pure ``(w, g, state_tuple) -> (w', state_tuple')`` dispatcher over the
    registered update kernels — the in-graph half of the Module fused train
    step (``module/fused_step.py``), where it runs once per parameter inside
    ONE donated jit alongside forward+vjp.

    ``lr``/``wd`` may be traced scalars; for ``adam`` the caller passes
    ``lr`` already bias-corrected (:func:`adam_bias_corrected_lr`) so the
    kernel runs with identity rescale.  ``state`` matches the optimizer's
    ``create_state`` order: ``()`` for sgd, ``(mom,)`` for sgd_mom,
    ``(mean, var)`` for adam.

    Every kernel here is elementwise over (weight, grad, state), so the
    update is sharding-neutral: under the sharded fused step GSPMD runs it
    on whatever partition the operands carry — full arrays on the
    replicated path, per-device 1/dp shards in ZeRO-1 mode (the grads'
    reduce-scatter and the params' allgather land around it for free).
    """
    if kind == "sgd":
        new_w = sgd_update(weight, grad, lr=lr, wd=wd,
                           rescale_grad=rescale_grad,
                           clip_gradient=clip_gradient)
        return new_w, ()
    if kind == "sgd_mom":
        new_w, new_mom = sgd_mom_update(weight, grad, state[0], lr=lr,
                                        momentum=momentum, wd=wd,
                                        rescale_grad=rescale_grad,
                                        clip_gradient=clip_gradient)
        return new_w, (new_mom,)
    if kind == "adam":
        # optimizer.Adam semantics clip BEFORE adding wd (its _preprocess +
        # adam_rule), while the adam_update kernel clips after — pre-scale
        # and clip here, then run the kernel with identity prep
        g = grad * rescale_grad
        if clip_gradient is not None and clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        new_w, new_m, new_v = adam_update(
            weight, g, state[0], state[1], lr=lr, beta1=beta1, beta2=beta2,
            epsilon=epsilon, wd=wd, rescale_grad=1.0, clip_gradient=-1.0)
        return new_w, (new_m, new_v)
    raise ValueError("unsupported fused optimizer kind %r" % (kind,))


@register("signum_update", mutates=("mom",))
def signum_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, wd_lh=0.0):
    """Signum: sign of momentum (reference optimizer_op.cc:69; Bernstein 2018)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1.0 - momentum) * (g + wd * weight)
    new_w = (1.0 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_w, new_mom
