"""Pallas TPU kernels for the quantization hot path.

The int8/uint8 (de)quantize ops (ops/quantization.py, reference
``src/operator/quantization/quantize-inl.h``) are pure HBM-bandwidth ops:
read fp32, write int8 + two scalars. The jnp formulation lowers to several
XLA ops (abs, max-reduce, scale, clip, round, cast) that XLA usually fuses —
these Pallas versions make the single-pass structure explicit (one VMEM tile
in, one tile out, scalar range in SMEM) and serve as the template for
further kernels (pallas_guide.md "Quantization Kernels" pattern).

Used automatically by the quantize/dequantize ops on TPU for tile-aligned
inputs; the jnp path remains the fallback (CPU tests run it via
``interpret=True`` coverage here).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["quantize_int8_pallas", "dequantize_int8_pallas", "supported"]

_LANE = 128
# minimum sublane count per dtype (pallas_guide.md tiling constraints)
_MIN_SUBLANES = {jnp.dtype(jnp.float32): 8, jnp.dtype(jnp.bfloat16): 16,
                 jnp.dtype(jnp.int8): 32}


def supported(shape, dtype):
    """Tile-aligned 2D-reshapeable arrays of a pallas-kernel dtype on TPU."""
    try:
        import jax.experimental.pallas  # noqa: F401
    except ImportError:  # pragma: no cover
        return False
    sub = _MIN_SUBLANES.get(jnp.dtype(dtype))
    if sub is None:
        return False
    n = 1
    for s in shape:
        n *= int(s)
    return n >= sub * _LANE and n % (sub * _LANE) == 0


def _q_kernel(x_ref, scale_ref, out_ref):
    """Symmetric int8: q = sign(x) * min(|x|*127/range + 0.5, 127)
    (reference quantize-inl.h:70-80)."""
    scale = scale_ref[0]
    x = x_ref[:]
    q = jnp.sign(x) * jnp.minimum(jnp.abs(x) * scale + 0.5, 127.0)
    out_ref[:] = q.astype(jnp.int8)


def _dq_kernel(q_ref, scale_ref, out_ref):
    out_ref[:] = q_ref[:].astype(jnp.float32) * scale_ref[0]


def _tiled_elementwise(kernel, x, scale, out_dtype, interpret):
    """Shared scaffolding: flatten to (rows, 128) tiles, grid over row
    blocks, scalar in SMEM — the template for further elementwise kernels."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape = x.shape
    flat = x.reshape(-1, _LANE)
    rows = flat.shape[0]
    block = min(rows, 512)
    while rows % block:
        block //= 2
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, out_dtype),
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, _LANE), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((block, _LANE), lambda i: (i, 0)),
        interpret=interpret,
    )(flat, scale)
    return out.reshape(shape)


@functools.partial(jax.jit, static_argnames=("interpret",))
def quantize_int8_pallas(x, real_range, interpret=False):
    """x: fp32 (any tile-aligned shape); real_range: scalar max-abs.
    Returns int8 of the same shape."""
    scale = (127.0 / real_range).reshape(1).astype(jnp.float32)
    return _tiled_elementwise(_q_kernel, x, scale, jnp.int8, interpret)


@functools.partial(jax.jit, static_argnames=("interpret",))
def dequantize_int8_pallas(q, real_range, interpret=False):
    """Inverse of quantize_int8_pallas."""
    scale = (real_range / 127.0).reshape(1).astype(jnp.float32)
    return _tiled_elementwise(_dq_kernel, q, scale, jnp.float32, interpret)
